"""Data subsystem tests (ref analog: reference exercises samplers in
test/parallel/test_torch_elastic.py and loaders in spark tests)."""

import threading
import time

import numpy as np
import pytest

from horovod_tpu.data import (AsyncDataLoader, AsyncDataLoaderMixin,
                              BaseDataLoader, DistributedSampler,
                              ElasticSampler, prefetch_to_device,
                              shard_batch_indices)


class TestAsyncLoader:
    def test_preserves_order_and_count(self):
        batches = [np.full((2,), i) for i in range(50)]
        loader = AsyncDataLoader(batches, async_loader_queue_size=4)
        out = list(loader)
        assert len(out) == 50
        for i, b in enumerate(out):
            np.testing.assert_array_equal(b, np.full((2,), i))
        loader.close()

    def test_queue_size_zero_is_sync(self):
        loader = AsyncDataLoader([1, 2, 3], async_loader_queue_size=0)
        assert list(loader) == [1, 2, 3]
        assert loader._thread is None  # never started a producer

    def test_producer_exception_reraises_in_consumer(self):
        class Exploding(AsyncDataLoaderMixin, BaseDataLoader):
            def _iterate(self):
                yield 1
                raise RuntimeError("boom in producer")

            def __len__(self):
                return 2

        loader = Exploding(async_loader_queue_size=2)
        it = iter(loader)
        assert next(it) == 1
        with pytest.raises(RuntimeError, match="boom in producer"):
            list(it)

    def test_process_batch_hook(self):
        class Doubler(AsyncDataLoader):
            def _process_batch(self, batch):
                return batch * 2

        assert list(Doubler([1, 2], async_loader_queue_size=2)) == [2, 4]

    def test_close_joins_blocked_producer(self):
        loader = AsyncDataLoader(list(range(1000)),
                                 async_loader_queue_size=1)
        it = iter(loader)
        next(it)  # producer now blocked on the full queue
        loader.close()
        assert loader._thread is None

    def test_reiteration_restarts(self):
        loader = AsyncDataLoader([1, 2, 3], async_loader_queue_size=2)
        assert list(loader) == [1, 2, 3]
        assert list(loader) == [1, 2, 3]

    def test_close_mid_iteration_with_full_queue(self):
        """Regression: close() must return promptly when the producer is
        parked on a FULL queue mid-iteration (the producer's bounded puts
        observe the stop flag; close drains and joins with a timeout)."""
        produced = []

        class Tracking(AsyncDataLoaderMixin, BaseDataLoader):
            def _iterate(self):
                for i in range(10_000):
                    produced.append(i)
                    yield i

            def __len__(self):
                return 10_000

        loader = Tracking(async_loader_queue_size=1)
        it = iter(loader)
        assert next(it) == 0
        # Let the producer refill the queue and block on the next put.
        deadline = time.monotonic() + 5.0
        while len(produced) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        t0 = time.monotonic()
        loader.close()
        assert time.monotonic() - t0 < 5.0, "close() hung on full queue"
        assert loader._thread is None
        # The producer really exited (it stopped far short of the
        # 10k-batch iterator).
        time.sleep(0.2)
        assert len(produced) < 100

    def test_close_bounded_when_producer_wedged_upstream(self):
        """A producer blocked inside the UPSTREAM iterator (not our
        queue) cannot be unblocked by draining; close() must still
        return within its bounded timeout and abandon the daemon."""
        release = threading.Event()

        class Wedged(AsyncDataLoaderMixin, BaseDataLoader):
            def _iterate(self):
                yield 1
                release.wait(30)   # simulates a stuck data source
                yield 2

            def __len__(self):
                return 2

        loader = Wedged(async_loader_queue_size=1,
                        close_timeout_s=0.3)
        it = iter(loader)
        assert next(it) == 1
        t0 = time.monotonic()
        loader.close()
        assert time.monotonic() - t0 < 2.0
        assert loader._thread is None
        release.set()   # let the daemon thread finish


class TestPrefetchToDevice:
    def test_yields_all_on_device(self, hvd):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = hvd.mesh()
        sharding = NamedSharding(mesh, P("dp"))
        batches = [np.arange(8.0) + i for i in range(7)]
        out = list(prefetch_to_device(batches, size=2, sharding=sharding))
        assert len(out) == 7
        for i, b in enumerate(out):
            assert isinstance(b, jax.Array)
            assert b.sharding == sharding
            np.testing.assert_array_equal(np.asarray(b), batches[i])

    def test_keeps_ahead(self):
        puts = []

        def put(x):
            puts.append(x)
            return x

        it = prefetch_to_device(range(5), size=3, put=put)
        next(it)
        # After one pop, the buffer should have been filled 3 deep +1 refill.
        assert len(puts) >= 3


class TestDistributedSampler:
    def test_partition_covers_all_no_overlap(self):
        parts = [list(DistributedSampler(10, shuffle=False, rank=r, size=3))
                 for r in range(3)]
        assert all(len(p) == 4 for p in parts)  # ceil(10/3)=4 padded
        covered = set()
        for p in parts:
            covered.update(p)
        assert covered == set(range(10))

    def test_drop_last(self):
        parts = [list(DistributedSampler(10, shuffle=False, rank=r, size=3,
                                         drop_last=True)) for r in range(3)]
        assert all(len(p) == 3 for p in parts)
        assert len({i for p in parts for i in p}) == 9

    def test_shuffle_deterministic_and_epoch_varies(self):
        s = DistributedSampler(100, shuffle=True, seed=5, rank=0, size=2)
        a = list(s)
        assert a == list(s)
        s.set_epoch(1)
        assert a != list(s)


class TestElasticSampler:
    def test_repartitions_remaining_after_rescale(self):
        # 2 workers process 2 batches of 4 (16 samples), then rescale to 4.
        s0 = ElasticSampler(64, shuffle=False, rank=0, size=2)
        s0.record_batch(0, 4)
        s0.record_batch(1, 4)
        state = s0.state_dict()
        assert state["processed_num"] == 16
        new = [ElasticSampler(64, shuffle=False, rank=r, size=4)
               for r in range(4)]
        for s in new:
            s.load_state_dict(state)
        remaining = {i for s in new for i in s}
        assert remaining == set(range(16, 64))
        assert all(len(s) == 12 for s in new)

    def test_set_epoch_clears_progress(self):
        s = ElasticSampler(8, shuffle=True, seed=1, rank=0, size=1)
        s.record_batch(0, 4)
        s.set_epoch(1)
        assert s.processed_num == 0
        assert len(list(s)) == 8

    def test_shuffled_split_consistent_across_ranks(self):
        samplers = [ElasticSampler(30, shuffle=True, seed=3, rank=r, size=3)
                    for r in range(3)]
        seen = [i for s in samplers for i in s]
        assert sorted(seen) == sorted(list(range(30)))


def test_shard_batch_indices():
    assert shard_batch_indices(8, rank=1, size=4) == slice(2, 4)
    with pytest.raises(ValueError, match="divisible"):
        shard_batch_indices(10, rank=0, size=4)
