"""RayExecutor Ray-branch tests (ref analogs: test/single/test_ray.py).

Ray is not in this image; the branch runs against a stub implementing
the exact surface the adapter touches (remote actor classes with
options/resources, ray.get, util.get_node_ip_address).  Actors execute
synchronously in process — actor placement env, resource options, the
rendezvous contract, and payload dispatch are what's under test.
"""

import sys
import types

import pytest


class _Ref:
    def __init__(self, value):
        self.value = value


class _ActorHandle:
    def __init__(self, cls, args, kwargs, stub):
        self._instance = cls(*args, **kwargs)
        self._stub = stub

    def __getattr__(self, name):
        method = getattr(self._instance, name)
        stub = self._stub

        class _Caller:
            @staticmethod
            def remote(*a, **kw):
                stub.calls.append((name, a, kw))
                return _Ref(method(*a, **kw))

        return _Caller()


class _RemoteClass:
    def __init__(self, cls, stub, options=None):
        self._cls, self._stub = cls, stub
        self.options_used = options or {}

    def options(self, **kw):
        rc = _RemoteClass(self._cls, self._stub, kw)
        self._stub.actor_options.append(kw)
        return rc

    def remote(self, *a, **kw):
        h = _ActorHandle(self._cls, a, kw, self._stub)
        self._stub.actors.append(h)
        return h


@pytest.fixture(autouse=True)
def _env_guard():
    """Stub actors run setup() in THIS process: restore os.environ so no
    stale HVDT_* contract (dead rendezvous, wrong rank) leaks into later
    tests."""
    import os

    before = dict(os.environ)
    yield
    os.environ.clear()
    os.environ.update(before)


@pytest.fixture()
def ray_stub(monkeypatch):
    stub = types.ModuleType("ray")
    stub.actors = []
    stub.actor_options = []
    stub.calls = []
    stub.node_ips = ["10.0.0.1"]
    stub._ip_iter = None
    stub.is_initialized = lambda: True
    stub.remote = lambda cls: _RemoteClass(cls, stub)

    def _get(refs, timeout=None):
        if isinstance(refs, list):
            return [r.value for r in refs]
        return refs.value

    stub.get = _get

    def _next_ip():
        if stub._ip_iter is None:
            ips = iter(stub.node_ips * 64)
            stub._ip_iter = ips
        return next(stub._ip_iter)

    stub.util = types.SimpleNamespace(get_node_ip_address=_next_ip)
    monkeypatch.setitem(sys.modules, "ray", stub)
    yield stub


def _setup_envs(stub):
    return [a[0] for name, a, kw in stub.calls if name == "setup"]


class TestRayBranch:
    def test_contract_and_layout(self, ray_stub):
        from horovod_tpu.orchestrate import RayExecutor

        ray_stub.node_ips = ["10.0.0.1", "10.0.0.1", "10.0.0.2",
                             "10.0.0.2"]
        ex = RayExecutor(num_workers=4, cpus_per_worker=2)
        ex.start()
        try:
            assert ex._use_ray
            assert ray_stub.actor_options == [{"num_cpus": 2}]
            envs = _setup_envs(ray_stub)
            assert [e["HVDT_RANK"] for e in envs] == ["0", "1", "2", "3"]
            assert [e["HVDT_LOCAL_RANK"] for e in envs] == \
                ["0", "1", "0", "1"]
            assert [e["HVDT_CROSS_RANK"] for e in envs] == \
                ["0", "0", "1", "1"]
            assert all(e["HVDT_SIZE"] == "4" for e in envs)
            assert all(e["HVDT_RENDEZVOUS_PORT"] for e in envs)
            assert all(e["HVDT_SECRET"] for e in envs)
            # JAX coordination service at rank 0's node: without this,
            # hvd.init() in actors would come up as size-1 islands.  The
            # port is reserved by the rank-0 actor (ephemeral, not a fixed
            # default that collides across concurrent jobs on one node).
            addrs = {e["HVDT_COORDINATOR_ADDR"] for e in envs}
            assert len(addrs) == 1
            host, port = addrs.pop().rsplit(":", 1)
            assert host == "10.0.0.1" and 1024 <= int(port) <= 65535
            assert any(name == "reserve_coordinator_port"
                       for name, _, _ in ray_stub.calls)
        finally:
            ex.shutdown()
        assert ex._ray_kv is None

    def test_pinned_coordinator_port(self, ray_stub):
        from horovod_tpu.orchestrate import RayExecutor

        ex = RayExecutor(num_workers=2, coordinator_port=29500)
        ex.start()
        try:
            envs = _setup_envs(ray_stub)
            assert all(e["HVDT_COORDINATOR_ADDR"] == "10.0.0.1:29500"
                       for e in envs)
        finally:
            ex.shutdown()

    def test_run_dispatches_through_actors(self, ray_stub, monkeypatch):
        from horovod_tpu.orchestrate import RayExecutor

        ex = RayExecutor(num_workers=2)
        ex.start()
        try:
            res = ex.run(lambda x=5: x * 2)
            assert res == [10, 10]
            refs = ex.run_remote(lambda: "ok")
            import ray

            assert ray.get(refs) == ["ok", "ok"]
        finally:
            ex.shutdown()

    def test_payload_class(self, ray_stub, monkeypatch):
        from horovod_tpu.orchestrate import RayExecutor

        class Trainer:
            def __init__(self, base):
                self.base = base

        ex = RayExecutor(num_workers=2)
        ex.start(executable_cls=Trainer, executable_args=(3,))
        try:
            res = ex.run(lambda t, y: t.base + y, args=(4,))
            assert res == [7, 7]
        finally:
            ex.shutdown()

    def test_gpu_options(self, ray_stub):
        from horovod_tpu.orchestrate import RayExecutor

        ex = RayExecutor(num_workers=1, use_gpu=True, gpus_per_worker=2)
        ex.start()
        try:
            assert ray_stub.actor_options == [{"num_cpus": 1,
                                               "num_gpus": 2}]
        finally:
            ex.shutdown()

    def test_failed_payload_does_not_leak_kv(self, ray_stub):
        from horovod_tpu.orchestrate import RayExecutor

        class Boom:
            def __init__(self):
                raise RuntimeError("payload exploded")

        ex = RayExecutor(num_workers=1)
        with pytest.raises(RuntimeError, match="payload exploded"):
            ex.start(executable_cls=Boom)
        assert ex._ray_kv is None
        assert ex._ray_workers == []
