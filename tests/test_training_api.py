"""DistributedOptimizer / functions / compression / sync-BN / callbacks.

Reference analogs: optimizer wrapper correctness via autograd
(test_torch.py DistributedOptimizer tests), broadcast_parameters/object
(test_torch.py test_broadcast_state), keras callback tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map


def test_distributed_optimizer_converges(hvd, mesh8):
    """DP training with DistributedOptimizer reaches the same solution as
    single-device training with the mean gradient."""
    opt = hvd.DistributedOptimizer(optax.sgd(0.1))

    w0 = jnp.zeros((3,))
    x = jnp.asarray(np.random.RandomState(0).randn(64, 3), jnp.float32)
    true_w = jnp.asarray([1.0, -2.0, 0.5])
    y = x @ true_w

    def step(w, opt_state, x, y):
        def per_shard(w, opt_state, xs, ys):
            g = jax.grad(lambda w: jnp.mean((xs @ w - ys) ** 2))(w)
            updates, opt_state = opt.update(g, opt_state, w)
            return optax.apply_updates(w, updates), opt_state
        return shard_map(per_shard, mesh=mesh8,
                         in_specs=(P(), P(), P("dp"), P("dp")),
                         out_specs=(P(), P()))(w, opt_state, x, y)

    opt_state = opt.init(w0)
    w = w0
    stepj = jax.jit(step)
    for i in range(1500):
        w, opt_state = stepj(w, opt_state, x, y)
        if i % 50 == 0:
            jax.block_until_ready(w)  # 1-core CPU: bound in-flight execs
    np.testing.assert_allclose(np.asarray(w), np.asarray(true_w), atol=1e-2)


def test_distributed_optimizer_grad_equivalence(hvd, mesh8):
    """One wrapped step == mean-of-shard-grads step."""
    opt = hvd.DistributedOptimizer(optax.sgd(1.0))
    w = jnp.asarray([1.0, 2.0])
    x = jnp.arange(16.0).reshape(8, 2)

    def loss(w, xs):
        return jnp.mean(jnp.sum(xs * w, axis=-1))

    def per_shard(w, opt_state, xs):
        g = jax.grad(loss)(w, xs)
        updates, opt_state = opt.update(g, opt_state, w)
        return optax.apply_updates(w, updates), opt_state

    opt_state = opt.init(w)
    w2, _ = shard_map(per_shard, mesh=mesh8,
                      in_specs=(P(), P(), P("dp")),
                      out_specs=(P(), P()))(w, opt_state, x)
    g_full = jax.grad(loss)(w, x)  # global mean gradient
    np.testing.assert_allclose(np.asarray(w2), np.asarray(w - g_full),
                               rtol=1e-6)


def test_backward_passes_per_step(hvd, mesh8):
    """MultiSteps aggregation: params move only every k-th step."""
    opt = hvd.DistributedOptimizer(optax.sgd(0.5), backward_passes_per_step=2)
    w = jnp.asarray([0.0])
    opt_state = opt.init(w)

    def per_shard(w, opt_state, g):
        updates, opt_state = opt.update(g[0], opt_state, w)
        return optax.apply_updates(w, updates), opt_state

    step = jax.jit(lambda w, s, g: shard_map(
        per_shard, mesh=mesh8, in_specs=(P(), P(), P("dp")),
        out_specs=(P(), P()))(w, s, g))

    g = jnp.ones((8, 1))
    w1, opt_state = step(w, opt_state, g)
    np.testing.assert_allclose(np.asarray(w1), [0.0])  # accumulating
    w2, opt_state = step(w1, opt_state, g)
    np.testing.assert_allclose(np.asarray(w2), [-0.5])  # applied mean grad


def test_compression_bf16_wire(hvd, mesh8):
    from horovod_tpu.ops.compression import Compression

    opt = hvd.DistributedOptimizer(optax.sgd(1.0),
                                   compression=Compression.bf16)
    w = jnp.asarray([0.0, 0.0])
    opt_state = opt.init(w)

    def per_shard(w, opt_state, g):
        updates, opt_state = opt.update(g[0], opt_state, w)
        return optax.apply_updates(w, updates), opt_state

    g = jnp.full((8, 2), 0.5)
    w2, _ = shard_map(per_shard, mesh=mesh8,
                      in_specs=(P(), P(), P("dp")), out_specs=(P(), P()))(
        w, opt_state, g)
    assert w2.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(w2), [-0.5, -0.5], rtol=1e-2)


def test_compressor_roundtrip():
    from horovod_tpu.ops.compression import Compression

    x = np.random.RandomState(0).randn(16).astype(np.float32)
    c, ctx = Compression.fp16.compress(x)
    assert c.dtype == np.float16
    out = Compression.fp16.decompress(c, ctx)
    assert out.dtype == np.float32
    np.testing.assert_allclose(out, x, atol=1e-2)
    i = np.arange(4)
    c, ctx = Compression.fp16.compress(i)
    assert ctx is None and c.dtype == i.dtype  # ints pass through


def test_broadcast_parameters(hvd):
    params = {"w": jnp.ones((4, 3)), "b": np.zeros(3, np.float32)}
    out = hvd.broadcast_parameters(params, root_rank=0)
    np.testing.assert_allclose(np.asarray(out["w"]), np.ones((4, 3)))
    np.testing.assert_allclose(np.asarray(out["b"]), np.zeros(3))


def test_broadcast_optimizer_state(hvd):
    opt = optax.adam(1e-3)
    params = {"w": jnp.ones((2, 2))}
    state = opt.init(params)
    out = hvd.broadcast_optimizer_state(state, root_rank=0)
    chex = jax.tree.leaves(out)
    assert len(chex) == len(jax.tree.leaves(state))


def test_broadcast_object(hvd):
    obj = {"epoch": 7, "name": "resnet", "lr": 0.1}
    out = hvd.broadcast_object(obj, root_rank=0)
    assert out == obj


def test_allgather_object(hvd):
    from horovod_tpu.functions import allgather_object

    out = allgather_object({"rank": hvd.rank()})
    assert out == [{"rank": 0}]


def test_average_metrics(hvd):
    from horovod_tpu.callbacks import average_metrics

    out = average_metrics({"loss": 2.0, "acc": 0.5})
    assert out == {"loss": 2.0, "acc": 0.5}


def test_warmup_schedule(hvd):
    from horovod_tpu.callbacks import warmup_schedule

    sched = warmup_schedule(0.1, warmup_steps=10, scale=8.0)
    np.testing.assert_allclose(float(sched(0)), 0.1, rtol=1e-6)
    np.testing.assert_allclose(float(sched(10)), 0.8, rtol=1e-6)
    np.testing.assert_allclose(float(sched(100)), 0.8, rtol=1e-6)
    mid = float(sched(5))
    assert 0.1 < mid < 0.8


def test_sync_batch_norm_stats(mesh8):
    from horovod_tpu.sync_batch_norm import sync_batch_stats

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, 5), jnp.float32)
    mean, var = shard_map(
        lambda t: sync_batch_stats(t, "dp"), mesh=mesh8,
        in_specs=(P("dp"),), out_specs=(P(), P()))(x)
    np.testing.assert_allclose(np.asarray(mean), np.asarray(x).mean(0),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(var), np.asarray(x).var(0),
                               atol=1e-5)


def test_sync_batch_norm_module(mesh8):
    import flax.linen as nn

    from horovod_tpu.sync_batch_norm import SyncBatchNorm

    bn = SyncBatchNorm(use_running_average=False, axis_name="dp")
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(32, 6), jnp.float32)
    variables = bn.init(jax.random.PRNGKey(0), x[:4])

    def per_shard(xs):
        y, _ = bn.apply(variables, xs, mutable=["batch_stats"])
        return y

    y = shard_map(per_shard, mesh=mesh8, in_specs=(P("dp"),),
                  out_specs=P("dp"))(x)
    # normalized with GLOBAL stats → global mean 0, var 1
    np.testing.assert_allclose(np.asarray(y).mean(0), np.zeros(6), atol=1e-5)
    np.testing.assert_allclose(np.asarray(y).std(0), np.ones(6), atol=1e-2)


def test_best_model_checkpoint(hvd, tmp_path):
    from horovod_tpu.callbacks import BestModelCheckpoint

    ckpt = BestModelCheckpoint(str(tmp_path / "best.pkl"), monitor="loss")
    assert ckpt({"loss": 1.0}, {"w": jnp.ones(2)})
    assert not ckpt({"loss": 2.0}, {"w": jnp.zeros(2)})
    assert ckpt({"loss": 0.5}, {"w": jnp.full((2,), 3.0)})
    import pickle

    with open(tmp_path / "best.pkl", "rb") as f:
        best = pickle.load(f)
    np.testing.assert_allclose(best["w"], [3.0, 3.0])


def test_microbatch_gradients(hvd, mesh8):
    """k micro-batches, one collective: equals the full-batch mean grad."""
    from horovod_tpu.optimizer import microbatch_gradients

    w = jnp.asarray([1.0, -1.0])
    x = jnp.asarray(np.random.RandomState(3).randn(64, 2), jnp.float32)

    def loss(w, xs):
        return jnp.mean((xs @ w) ** 2)

    def grad_fn(w, xs):
        return jax.grad(loss)(w, xs)

    def per_shard(w, xs):
        return microbatch_gradients(grad_fn, w, xs, num_microbatches=4)

    g = shard_map(per_shard, mesh=mesh8, in_specs=(P(), P("dp")),
                  out_specs=P())(w, x)
    g_full = jax.grad(loss)(w, x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_full), rtol=1e-5)


def test_distributed_optimizer_adasum_jit_path(hvd):
    """End-to-end Adasum through DistributedOptimizer under shard_map:
    per-rank gradients stay varying (pvary_tree), the combine runs in
    jit, and outputs are replicated (VMA-invariant)."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd_mod
    from horovod_tpu.optimizer import pvary_tree

    mesh = hvd_mod.mesh()
    opt = hvd_mod.DistributedOptimizer(optax.sgd(0.1), op=hvd_mod.Adasum)
    params = {"w": jnp.ones(4)}
    opt_state = opt.init(params)

    def local_step(params, opt_state, x):
        def loss_fn(p):
            return jnp.sum(p["w"] * x)

        grads = jax.grad(loss_fn)(pvary_tree(params, "dp"))
        updates, opt_state2 = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state2

    step = jax.jit(jax.shard_map(
        local_step, mesh=mesh, in_specs=(P(), P(), P("dp")),
        out_specs=(P(), P())))
    # Identical per-rank grads x=1: adasum of identical vectors is the
    # vector itself (scale invariance) -> w goes 1.0 -> 1.0 - 0.1*1.
    x = jnp.ones(8)
    new_params, _ = step(params, opt_state, x)
    import numpy as np

    np.testing.assert_allclose(np.asarray(new_params["w"]),
                               np.full(4, 0.9), rtol=1e-6)
