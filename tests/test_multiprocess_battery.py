"""Multi-rank eager-collective battery — real worker processes.

The analog of the reference's parallel test tier run under
``mpirun -np {2,4} pytest`` (ref: test/parallel/test_torch.py:59 and its
error-case battery): every negotiated eager op exercised across true
process boundaries, including the ragged/uneven/error paths that size-1
tests cannot reach.  Packed into one worker function per process count
(process spawn + JAX import dominate, so each np config boots once).
"""

import numpy as np
import pytest


def _battery4():
    """np=4 op battery; returns {check_name: payload} per rank."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r, s = hvd.rank(), hvd.size()
    out = {"rank": r, "size": s}

    # -- reduce ops across 4 ranks ----------------------------------------
    base = np.array([float(r + 1), float(2 * r + 1)], np.float32)
    out["avg"] = np.asarray(
        hvd.allreduce(base, name="b_avg", op=hvd.Average)).tolist()
    out["sum"] = np.asarray(
        hvd.allreduce(base, name="b_sum", op=hvd.Sum)).tolist()
    out["min"] = np.asarray(
        hvd.allreduce(base, name="b_min", op=hvd.Min)).tolist()
    out["max"] = np.asarray(
        hvd.allreduce(base, name="b_max", op=hvd.Max)).tolist()
    out["prod"] = np.asarray(
        hvd.allreduce(np.full(2, float(r + 1), np.float32), name="b_prod",
                      op=hvd.Product)).tolist()

    # -- eager Adasum across ranks ----------------------------------------
    ada = hvd.allreduce(np.full(3, float(r + 1), np.float32),
                       name="b_ada", op=hvd.Adasum)
    out["adasum"] = np.asarray(ada).tolist()

    # -- ragged allgather: rank r contributes r+1 rows --------------------
    g = hvd.allgather(np.full((r + 1, 2), float(r), np.float32), name="b_ag")
    out["allgather"] = np.asarray(g).tolist()

    # -- uneven alltoall: rank r sends ((r+j) % 2) + 1 rows to rank j -----
    splits = [((r + j) % 2) + 1 for j in range(s)]
    payload = np.concatenate([
        np.full((splits[j], 1), 10.0 * r + j, np.float32)
        for j in range(s)])
    recv, rsplits = hvd.alltoall(payload, splits=splits, name="b_a2a")
    out["alltoall"] = (np.asarray(recv).ravel().tolist(),
                       list(np.asarray(rsplits)))

    # -- reducescatter (uneven tail goes to low ranks first) --------------
    rs = hvd.reducescatter(np.arange(8, dtype=np.float32), name="b_rs",
                           op=hvd.Sum)
    out["reducescatter"] = np.asarray(rs).tolist()

    # -- process-set subgroups: low pair vs high pair ---------------------
    lo = hvd.add_process_set([0, 1])
    hi = hvd.add_process_set([2, 3])
    mine = lo if r < 2 else hi
    sub = hvd.allreduce(np.full(2, float(r), np.float32), name="b_sub",
                        op=hvd.Sum, process_set=mine)
    out["subgroup"] = np.asarray(sub).tolist()

    # -- join with pending tensors: ranks 0-2 allreduce, rank 3 joins -----
    if r != 3:
        pend = hvd.allreduce(np.full(2, float(r + 1), np.float32),
                             name="b_pend", op=hvd.Sum)
        out["join_pending"] = np.asarray(pend).tolist()
        last = hvd.join()
    else:
        last = hvd.join()          # no matching enqueue: zero contribution
        out["join_pending"] = None
    out["join_last"] = int(last)

    hvd.shutdown()
    return out


def _errors2():
    """np=2 cross-rank error battery."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    out = {"rank": r}

    # shape mismatch across ranks must raise on EVERY rank, not hang
    try:
        hvd.allreduce(np.zeros(3 if r == 0 else 4, np.float32),
                      name="err_shape")
        out["shape_mismatch"] = "no-error"
    except Exception as e:
        out["shape_mismatch"] = type(e).__name__ + ":" + str(e)[:80]

    # dtype mismatch across ranks
    try:
        hvd.allreduce(
            np.zeros(3, np.float32 if r == 0 else np.float64),
            name="err_dtype")
        out["dtype_mismatch"] = "no-error"
    except Exception as e:
        out["dtype_mismatch"] = type(e).__name__ + ":" + str(e)[:80]

    # mismatched op across ranks
    try:
        hvd.allreduce(np.zeros(3, np.float32), name="err_op",
                      op=hvd.Sum if r == 0 else hvd.Average)
        out["op_mismatch"] = "no-error"
    except Exception as e:
        out["op_mismatch"] = type(e).__name__ + ":" + str(e)[:80]

    # the controller must still be usable after failed negotiations
    ok = hvd.allreduce(np.full(2, float(r + 1), np.float32),
                       name="err_recover", op=hvd.Sum)
    out["recovered"] = np.asarray(ok).tolist()
    hvd.shutdown()
    return out


from conftest import pickle_by_value as _pickled


def test_four_process_battery():
    import horovod_tpu.runner as runner

    results = runner.run(_pickled(_battery4), np=4)
    assert len(results) == 4
    by_rank = sorted(results, key=lambda o: o["rank"])
    s = 4
    for r, out in enumerate(by_rank):
        assert out["size"] == s
        np.testing.assert_allclose(out["avg"], [2.5, 4.0])     # mean r+1 / 2r+1
        np.testing.assert_allclose(out["sum"], [10.0, 16.0])
        np.testing.assert_allclose(out["min"], [1.0, 1.0])
        np.testing.assert_allclose(out["max"], [4.0, 7.0])
        np.testing.assert_allclose(out["prod"], [24.0, 24.0])  # 1*2*3*4

        # Adasum of parallel vectors collapses toward the dominant
        # direction; exact value checked for cross-rank agreement below.

        # ragged allgather: rows 1+2+3+4 = 10, rank-major order
        ag = np.asarray(out["allgather"])
        assert ag.shape == (10, 2)
        expect_rows = sum(([float(q)] * (q + 1) for q in range(s)), [])
        np.testing.assert_allclose(ag[:, 0], expect_rows)

        # uneven alltoall: rank r receives split ((q+r)%2)+1 from each q
        vals, rsplits = out["alltoall"]
        expect_splits = [((q + r) % 2) + 1 for q in range(s)]
        assert list(rsplits) == expect_splits
        expect_vals = sum(([10.0 * q + r] * expect_splits[q]
                           for q in range(s)), [])
        np.testing.assert_allclose(vals, expect_vals)

        # reducescatter of arange(8) summed over 4 ranks, split 2 each
        np.testing.assert_allclose(
            out["reducescatter"],
            (4 * np.arange(8, dtype=np.float64))[2 * r:2 * r + 2])

        # subgroups: 0+1=1 for the low pair, 2+3=5 for the high pair
        np.testing.assert_allclose(
            out["subgroup"], [1.0, 1.0] if r < 2 else [5.0, 5.0])

        # join: ranks 0-2's pending sum completes with rank 3 absent
        # (zero contribution): 1+2+3 = 6
        if r != 3:
            np.testing.assert_allclose(out["join_pending"], [6.0, 6.0])

    # cross-rank agreement for adasum + join ordering
    ada0 = by_rank[0]["adasum"]
    for out in by_rank[1:]:
        np.testing.assert_allclose(out["adasum"], ada0)
    assert len({o["join_last"] for o in by_rank}) == 1


def test_two_process_error_battery():
    import horovod_tpu.runner as runner

    results = runner.run(_pickled(_errors2), np=2)
    by_rank = sorted(results, key=lambda o: o["rank"])
    for out in by_rank:
        # every rank sees the negotiation error, with the reason named
        assert out["shape_mismatch"] != "no-error"
        assert "shape" in out["shape_mismatch"].lower()
        assert out["dtype_mismatch"] != "no-error"
        assert "type" in out["dtype_mismatch"].lower()
        assert out["op_mismatch"] != "no-error"
        # and the controller keeps working afterwards
        np.testing.assert_allclose(out["recovered"], [3.0, 3.0])


def _worker_64bit():
    """64-bit dtype regression: without x64, device_put used to corrupt
    int64 through the host data plane (negative MAX clamped to 0)."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    out = {}
    out["max"] = np.asarray(hvd.allreduce(
        np.asarray([120, -120 - r], np.int64), op=hvd.Max,
        name="i64max")).tolist()
    out["min"] = np.asarray(hvd.allreduce(
        np.asarray([7 + r, -5], np.int64), op=hvd.Min,
        name="i64min")).tolist()
    try:
        hvd.allreduce(np.asarray([2 ** 40], np.int64), op=hvd.Max,
                      name="i64big")
        out["overflow"] = "no error"
    except Exception as e:
        out["overflow"] = type(e).__name__
    hvd.shutdown()
    return out


def test_two_process_int64_minmax():
    from conftest import pickle_by_value

    import horovod_tpu.runner as runner

    results = runner.run(pickle_by_value(_worker_64bit), np=2)
    for out in results:
        assert out["max"] == [120, -120]
        assert out["min"] == [7, -5]
        # raised synchronously at the call site (enqueue-time check) so
        # peers are never stranded mid-collective
        assert out["overflow"] == "ValueError"


def _worker_scalar_broadcast():
    """0-d tensors through broadcast/allreduce (regression: the host
    broadcast path desynced its per-device buffers from the negotiated
    () shape because np.ascontiguousarray promotes 0-d to (1,) — hit by
    Keras optimizer iteration counters in BroadcastGlobalVariables)."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.rank()
    out = {"rank": r}
    # scalar int32 broadcast (root's value wins), mixed with array leaves
    tree = {"it": np.int32(100 + r), "w": np.full((2, 2), float(r))}
    synced = hvd.broadcast_parameters(tree, root_rank=0)
    out["it"] = int(synced["it"])
    out["it_shape"] = list(np.shape(synced["it"]))
    out["w0"] = float(np.asarray(synced["w"]).ravel()[0])
    # scalar float allreduce
    out["m"] = float(np.asarray(hvd.allreduce(np.float32(r + 1.0),
                                              name="sc_m")))
    # repeat with a NEW shape under the SAME names (cache invalidation)
    tree2 = {"it": np.full((3,), r, np.float32), "w": np.float32(r)}
    synced2 = hvd.broadcast_parameters(tree2, root_rank=1)
    out["it2"] = np.asarray(synced2["it"]).tolist()
    out["w2"] = float(synced2["w"])
    hvd.shutdown()
    return out


@pytest.mark.integration
def test_two_process_scalar_broadcast():
    from conftest import pickle_by_value

    import horovod_tpu.runner as runner

    results = runner.run(pickle_by_value(_worker_scalar_broadcast), np=2)
    for out in results:
        assert out["it"] == 100, out
        assert out["it_shape"] == [], out
        assert out["w0"] == 0.0, out
        assert abs(out["m"] - 1.5) < 1e-6, out
        assert out["it2"] == [1.0, 1.0, 1.0], out
        assert out["w2"] == 1.0, out


def _battery8():
    """np=8 combined scenario (VERDICT r2 missing #6): negotiated eager
    path at full width with process sets + join + stall detection in ONE
    run — the widest single-controller exercise in the suite."""
    import os
    import time

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["HVDT_STALL_CHECK_TIME_SECONDS"] = "1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r, s = hvd.rank(), hvd.size()
    out = {"rank": r, "size": s}

    # -- full-width reduce ------------------------------------------------
    out["sum8"] = np.asarray(hvd.allreduce(
        np.full(3, float(r + 1), np.float32), name="b8_sum",
        op=hvd.Sum)).tolist()

    # -- process sets: two disjoint sets of 4, reduced independently ------
    low = hvd.add_process_set(list(range(4)))
    high = hvd.add_process_set(list(range(4, 8)))
    ps = low if r < 4 else high
    out["ps_sum"] = np.asarray(hvd.allreduce(
        np.full(2, float(r + 1), np.float32), name="b8_ps", op=hvd.Sum,
        process_set=ps)).tolist()

    # -- stall: rank 7 submits LATE (past the 1s warn threshold); the op
    # must still complete, and rank 0's coordinator must have logged the
    # stall warning for it.
    if r == 7:
        time.sleep(2.5)
    out["stalled"] = np.asarray(hvd.allreduce(
        np.full(2, 1.0, np.float32), name="b8_stall", op=hvd.Sum)).tolist()
    if r == 0:
        ctl = hvd.common.basics._state.eager_controller
        deadline = time.time() + 10
        warned = False
        while time.time() < deadline and not warned:
            warned = any("b8_stall" in w for w in ctl._stall.warned_ever)
            time.sleep(0.1)
        out["stall_warned"] = warned

    # -- join: rank 5 leaves; remaining ranks' pending op completes -------
    if r != 5:
        out["join_sum"] = np.asarray(hvd.allreduce(
            np.full(2, float(r + 1), np.float32), name="b8_join",
            op=hvd.Sum)).tolist()
    out["join_last"] = int(hvd.join())

    hvd.shutdown()
    return out


@pytest.mark.integration
def test_eight_process_combined_scenario():
    import horovod_tpu.runner as runner

    results = runner.run(_pickled(_battery8), np=8)
    assert len(results) == 8
    by_rank = sorted(results, key=lambda o: o["rank"])
    for r, out in enumerate(by_rank):
        assert out["size"] == 8
        np.testing.assert_allclose(out["sum8"], [36.0] * 3)    # 1+..+8
        expect = 10.0 if r < 4 else 26.0                       # 1..4 / 5..8
        np.testing.assert_allclose(out["ps_sum"], [expect] * 2)
        np.testing.assert_allclose(out["stalled"], [8.0] * 2)
        if r != 5:
            # join: sum over the 7 surviving ranks (1..8 minus 6)
            np.testing.assert_allclose(out["join_sum"], [30.0] * 2)
    assert by_rank[0]["stall_warned"] is True
    assert len({o["join_last"] for o in by_rank}) == 1
