"""Continuous-goodput battery: async non-blocking checkpoints, the
peer-replicated RAM tier, the recovery-time budget, and deterministic
data resume (ROADMAP item 4).

The multiprocess scenario tests at the bottom are the acceptance bar:
kill rank 1 (and, pod variant, a whole pod) mid-run with
``HVDT_ASYNC_CKPT=1`` + ``HVDT_PEER_STORE=1`` and prove recovery came
from the surviving peer RAM tier (no disk restore), landed inside the
30 s budget, and replayed zero committed batches.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from horovod_tpu.checkpoint import CheckpointManager  # noqa: E402
from horovod_tpu.resilience import faults  # noqa: E402
from horovod_tpu.resilience import peer_store as peer_store_mod  # noqa: E402
from horovod_tpu.resilience.peer_store import PeerStore  # noqa: E402
from horovod_tpu.runner.http_kv import KVClient, RendezvousServer  # noqa: E402
from horovod_tpu.telemetry import step_stats  # noqa: E402
from horovod_tpu.telemetry.metrics import (MetricsRegistry,  # noqa: E402
                                           reset_default_registry)


@pytest.fixture(autouse=True)
def _clean_goodput_state(monkeypatch):
    """Each test gets a fresh default registry, recovery ledger, fault
    plan, and peer-store cache — all four are process-wide singletons."""
    monkeypatch.delenv("HVDT_ASYNC_CKPT", raising=False)
    monkeypatch.delenv("HVDT_PEER_STORE", raising=False)
    monkeypatch.delenv("HVDT_FAULT_PLAN", raising=False)
    reset_default_registry()
    step_stats.reset_recovery_ledger()
    peer_store_mod.reset()
    faults.configure(None)
    yield
    reset_default_registry()
    step_stats.reset_recovery_ledger()
    peer_store_mod.reset()
    faults.configure(None)


def _tree(k=1.0):
    return {"w": jnp.ones(8) * k, "b": np.arange(4.0) * k}


# ---------------------------------------------------------------------------
# Async checkpointing
# ---------------------------------------------------------------------------

class TestAsyncCheckpoint:
    def test_identity_contract_when_unset(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "c"))
        # The faults/telemetry/overlap idiom: no knob, no wrapper — the
        # attribute IS the synchronous save.
        assert mgr.save_async == mgr.save
        assert mgr.save_async.__func__ is CheckpointManager.save

    def test_async_write_advances_last_good(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HVDT_ASYNC_CKPT", "1")
        mgr = CheckpointManager(str(tmp_path / "c"))
        assert mgr.save_async.__func__ is not CheckpointManager.save
        assert mgr.last_good_step() is None
        assert mgr.save_async(3, _tree(3.0), force=True)
        assert mgr.wait_for_async(30)
        assert mgr.last_good_step() == 3
        assert mgr.verify_step(3)
        tree, step = mgr.restore_latest(_tree(0.0), broadcast=False)
        assert step == 3
        np.testing.assert_allclose(np.asarray(tree["w"]), 3.0)
        mgr.close()

    def test_interval_respected(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HVDT_ASYNC_CKPT", "1")
        mgr = CheckpointManager(str(tmp_path / "c"), save_interval_steps=5)
        assert not mgr.save_async(3, _tree())
        assert mgr.save_async(5, _tree())
        assert mgr.wait_for_async(30)
        assert mgr.last_good_step() == 5
        mgr.close()

    def test_newer_snapshot_supersedes_queued(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HVDT_ASYNC_CKPT", "1")
        mgr = CheckpointManager(str(tmp_path / "c"), max_to_keep=10)
        gate = threading.Event()
        orig = CheckpointManager._write_step_payload

        def gated(self, step, payload):
            gate.wait(30)
            orig(self, step, payload)

        monkeypatch.setattr(CheckpointManager, "_write_step_payload", gated)
        mgr.save_async(1, _tree(1.0), force=True)   # writer blocks on gate
        deadline = time.monotonic() + 5
        while not mgr._writer._busy and time.monotonic() < deadline:
            time.sleep(0.01)                        # let it pick up step 1
        assert mgr._writer._busy
        mgr.save_async(2, _tree(2.0), force=True)   # queued
        mgr.save_async(3, _tree(3.0), force=True)   # supersedes step 2
        gate.set()
        assert mgr.wait_for_async(30)
        assert mgr.last_good_step() == 3
        assert mgr.all_steps() == [1, 3]            # step 2 never written
        reg = mgr._async_metrics()
        assert reg["superseded"].total() == 1
        mgr.close()

    def test_write_failure_keeps_last_good(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HVDT_ASYNC_CKPT", "1")
        mgr = CheckpointManager(str(tmp_path / "c"))
        mgr.save_async(1, _tree(1.0), force=True)
        assert mgr.wait_for_async(30)
        assert mgr.last_good_step() == 1

        def boom(self, step, payload):
            raise OSError("disk on fire")

        monkeypatch.setattr(CheckpointManager, "_write_step_payload", boom)
        mgr.save_async(2, _tree(2.0), force=True)
        assert mgr.wait_for_async(30)
        assert mgr.last_good_step() == 1            # pointer never moved
        assert mgr._async_metrics()["failures"].total() == 1
        mgr.close()

    def test_snapshot_budget_counter(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HVDT_ASYNC_CKPT", "1")
        monkeypatch.setenv("HVDT_CKPT_SNAPSHOT_BUDGET_S", "0")
        mgr = CheckpointManager(str(tmp_path / "c"))
        mgr.save_async(1, _tree(), force=True)
        assert mgr.wait_for_async(30)
        assert mgr._async_metrics()["over_budget"].total() >= 1
        assert mgr._async_metrics()["snapshot"].count >= 1
        mgr.close()

    def test_nonblocking_under_slow_disk(self, tmp_path, monkeypatch):
        """The acceptance proof: under slow_disk@step=N:secs=S the step
        loop stays within 2x of baseline while the background write is
        in flight — and LAST_GOOD still only advances after a verified
        manifest."""
        from horovod_tpu.telemetry.step_stats import StepTimer

        step_sleep = 0.05
        baseline = StepTimer(registry=MetricsRegistry())
        for _ in range(4):
            with baseline.step():
                time.sleep(step_sleep)

        monkeypatch.setenv("HVDT_ASYNC_CKPT", "1")
        monkeypatch.setenv("HVDT_FAULT_PLAN", "slow_disk@step=1:secs=1.5")
        mgr = CheckpointManager(str(tmp_path / "c"))
        timed = StepTimer(registry=MetricsRegistry())
        tree = _tree()
        for i in range(1, 5):
            with timed.step():
                time.sleep(step_sleep)
                mgr.save_async(i, tree, force=True)
        # The 1.5 s injected fsync stall must not have surfaced in any
        # step: mean within 2x of the no-checkpoint baseline.
        assert timed.mean_step_seconds() < 2 * baseline.mean_step_seconds()
        assert mgr.wait_for_async(30)
        good = mgr.last_good_step()
        assert good is not None and good >= 1
        assert mgr.verify_step(good)
        mgr.close()

    def test_sync_save_stalls_under_slow_disk(self, tmp_path, monkeypatch):
        """Control leg: the same fault at the same seam DOES stall the
        synchronous save — proving the fault fires where claimed."""
        monkeypatch.setenv("HVDT_FAULT_PLAN", "slow_disk@step=1:secs=0.4")
        mgr = CheckpointManager(str(tmp_path / "c"))
        t0 = time.perf_counter()
        mgr.save(1, _tree(), force=True)
        assert time.perf_counter() - t0 >= 0.4


# ---------------------------------------------------------------------------
# Durable manifests + torn-manifest fault (satellite)
# ---------------------------------------------------------------------------

class TestDurableManifest:
    def test_truncated_manifest_fails_verification(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "c"), max_to_keep=10)
        mgr.save(1, _tree(1.0), force=True)
        mgr.save(2, _tree(2.0), force=True)
        assert mgr.verify_step(2)
        assert faults.truncate_file(mgr._manifest_path(2))
        assert not mgr.verify_step(2)
        tree, step = mgr.restore_latest(_tree(0.0), broadcast=False)
        assert step == 1
        assert mgr.corrupt_detected == 1

    def test_corrupt_ckpt_truncate_manifest_plan(self, tmp_path,
                                                 monkeypatch):
        """The new fault-plan variant: the manifest of the step-2 save
        is truncated between write and LAST_GOOD advance — restore must
        fall back to step 1 without crashing."""
        mgr = CheckpointManager(str(tmp_path / "c"), max_to_keep=10)
        mgr.save(1, _tree(1.0), force=True)
        monkeypatch.setenv(
            "HVDT_FAULT_PLAN", "corrupt_ckpt@step=2:mode=truncate_manifest")
        mgr.save(2, _tree(2.0), force=True)
        assert not mgr.verify_step(2)
        tree, step = mgr.restore_latest(_tree(0.0), broadcast=False)
        assert step == 1
        np.testing.assert_allclose(np.asarray(tree["w"]), 1.0)

    def test_manifest_and_pointer_are_fsynced(self, tmp_path, monkeypatch):
        synced = []
        real_fsync = os.fsync
        monkeypatch.setattr(os, "fsync",
                            lambda fd: (synced.append(fd), real_fsync(fd)))
        mgr = CheckpointManager(str(tmp_path / "c"))
        mgr.save(1, _tree(), force=True)
        # manifest file + directory + LAST_GOOD tmp + directory again.
        assert len(synced) >= 4

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError, match="truncate_manifest"):
            faults.parse_plan("corrupt_ckpt@step=1:mode=shred")

    def test_slow_disk_grammar(self):
        spec = faults.parse_plan("slow_disk@step=8:secs=5")[0]
        assert spec.kind == "slow_disk"
        assert spec.point == "checkpoint.write"
        assert spec.secs == 5.0
        assert spec.times == 1


# ---------------------------------------------------------------------------
# Serve reload skips unverified steps (satellite)
# ---------------------------------------------------------------------------

class TestReloadSkipsUnverified:
    def test_truncated_manifest_falls_back_immediately(self, hvd, tmp_path):
        from horovod_tpu.serve.reload import CheckpointWatcher

        mgr = CheckpointManager(str(tmp_path / "c"), max_to_keep=10)
        mgr.save(1, _tree(1.0), force=True)
        mgr.save(2, _tree(2.0), force=True)
        faults.truncate_file(mgr._manifest_path(2))
        seen = []
        watcher = CheckpointWatcher(
            mgr, template=_tree(0.0),
            on_reload=lambda tree, step: seen.append(step),
            poll_interval_s=0.05)
        # The corrupt newest step is skipped, the previous good step
        # loads, and the failure backoff is NOT charged.
        assert watcher.check_once() == 1
        assert watcher._fail_streak == 0
        assert seen == [1]
        assert "serve_skipped_unverified_total 1" in watcher.metrics.render()
        # A verified newer step loads on the next poll.
        mgr.save(3, _tree(3.0), force=True)
        assert watcher.check_once() == 3
        assert seen == [1, 3]


# ---------------------------------------------------------------------------
# Recovery-time budget ledger
# ---------------------------------------------------------------------------

class TestRecoveryLedger:
    def test_phase_attribution_and_metric(self):
        reg = MetricsRegistry()
        ledger = step_stats.GoodputLedger(registry=reg)
        ledger.charge_phase("restore", 1.5)
        ledger.charge_phase("rendezvous", 0.5)
        ledger.charge_phase("restore", 0.5)
        assert ledger.recovery_seconds("restore") == 2.0
        assert ledger.recovery_seconds() == 2.5
        assert ledger.recovery_snapshot() == {
            "restore": 2.0, "rendezvous": 0.5}
        counter = reg.get("hvdt_recovery_seconds")
        assert counter.value(phase="restore") == 2.0
        assert counter.value(phase="rendezvous") == 0.5
        # Non-overlapped phases also charge the goodput bill.
        assert ledger.lost_seconds("restore") == 2.0

    def test_unknown_phase_raises(self):
        ledger = step_stats.GoodputLedger(registry=MetricsRegistry())
        with pytest.raises(ValueError, match="checkpoint_snapshot"):
            ledger.charge_phase("coffee_break", 1.0)

    def test_overlapped_phase_not_charged_to_goodput(self):
        now = [100.0]
        ledger = step_stats.GoodputLedger(registry=MetricsRegistry(),
                                          clock=lambda: now[0])
        ledger.charge_phase("checkpoint_write", 5.0, overlapped=True)
        now[0] += 10.0
        assert ledger.recovery_seconds("checkpoint_write") == 5.0
        assert ledger.lost_seconds() == 0.0
        assert ledger.fraction() == 1.0

    def test_phase_context_manager(self):
        now = [0.0]
        ledger = step_stats.GoodputLedger(registry=MetricsRegistry(),
                                          clock=lambda: now[0])
        with ledger.phase("rendezvous"):
            now[0] += 3.0
        assert ledger.recovery_seconds("rendezvous") == 3.0

    def test_recovery_ledger_zero_overhead_contract(self, monkeypatch):
        monkeypatch.delenv("HVDT_TELEMETRY", raising=False)
        step_stats.reset_recovery_ledger()
        assert step_stats.recovery_ledger() is None
        monkeypatch.setenv("HVDT_TELEMETRY", "1")
        ledger = step_stats.recovery_ledger()
        assert ledger is not None
        assert step_stats.recovery_ledger() is ledger


# ---------------------------------------------------------------------------
# Peer store
# ---------------------------------------------------------------------------

@pytest.fixture()
def kv_server():
    srv = RendezvousServer(port=0, addr="127.0.0.1")
    srv.start()
    yield srv
    srv.stop()


def _client(srv):
    return KVClient("127.0.0.1", srv.port, srv.secret)


class TestPeerStore:
    def test_commit_restore_roundtrip(self, kv_server):
        kv = _client(kv_server)
        ps = PeerStore(kv, rank=1, size=2, registry=MetricsRegistry())
        snap = {"w": np.arange(4.0), "batch": 7}
        assert ps.commit(7, snap)
        assert ps.peek_step() == 7
        got, step = ps.restore()
        assert step == 7
        np.testing.assert_array_equal(got["w"], snap["w"])
        assert ps.restore_count() == 1

    def test_corrupt_replica_is_a_miss(self, kv_server):
        kv = _client(kv_server)
        reg = MetricsRegistry()
        ps = PeerStore(kv, rank=0, size=1, registry=reg)
        ps.commit(3, {"x": 1})
        kv_server.put_local("/peer/0", b"HVPS1\x00\x00\x00\x05kaput")
        assert ps.restore() is None
        assert reg.get("hvdt_peer_miss_total").total() == 1
        assert ps.restore_count() == 0

    def test_ram_replica_served_back_after_kv_loss(self, kv_server):
        """rank 0 mirrors rank 1's snapshot; when the KV forgets it,
        serve_replicas re-offers the RAM copy and rank 1 restores."""
        kv = _client(kv_server)
        ps0 = PeerStore(kv, rank=0, size=2, registry=MetricsRegistry())
        ps1 = PeerStore(kv, rank=1, size=2, registry=MetricsRegistry())
        ps1.commit(9, {"w": np.ones(2)})
        assert ps0.refresh_replica() == 9        # rank 0 watches rank 1
        with kv_server.lock:
            kv_server.store.pop("/peer/1")
        assert ps1.restore() is None             # KV lost it...
        assert ps0.serve_replicas() == 1         # ...RAM tier re-offers
        got, step = ps1.restore()
        assert step == 9

    def test_newer_commit_refreshes_replica(self, kv_server):
        kv = _client(kv_server)
        ps0 = PeerStore(kv, rank=0, size=2, registry=MetricsRegistry())
        ps1 = PeerStore(kv, rank=1, size=2, registry=MetricsRegistry())
        ps1.commit(1, {"v": 1})
        ps0.refresh_replica()
        ps1.commit(2, {"v": 2})
        assert ps0.refresh_replica() == 2
        got, step = ps1.restore()
        assert (got["v"], step) == (2, 2)

    def test_zero_shard_rows_roundtrip(self, kv_server):
        from horovod_tpu.ops import zero as zero_mod

        kv = _client(kv_server)
        ps = PeerStore(kv, rank=2, size=4, registry=MetricsRegistry())
        state = zero_mod.ZeroSgdState(
            trace=(jnp.arange(12, dtype=jnp.float32).reshape(4, 3),))
        assert ps.commit_zero_shard(state, step=5)
        blank = zero_mod.ZeroSgdState(
            trace=(jnp.zeros((4, 3), jnp.float32),))
        restored, step = ps.restore_zero_shard(blank)
        assert step == 5
        got = np.asarray(restored.trace[0])
        np.testing.assert_array_equal(got[2], [6.0, 7.0, 8.0])
        np.testing.assert_array_equal(got[0], 0.0)   # other rows untouched

    def test_env_contract(self, kv_server, monkeypatch):
        # Unset: None, no wrappers anywhere.
        assert peer_store_mod.get_peer_store() is None
        monkeypatch.setenv("HVDT_PEER_STORE", "1")
        # Knob set but no rendezvous env: still None (no transport).
        monkeypatch.delenv("HVDT_RENDEZVOUS_ADDR", raising=False)
        peer_store_mod.reset()
        assert peer_store_mod.get_peer_store() is None
        monkeypatch.setenv("HVDT_RENDEZVOUS_ADDR", "127.0.0.1")
        monkeypatch.setenv("HVDT_RENDEZVOUS_PORT", str(kv_server.port))
        monkeypatch.setenv("HVDT_SECRET", kv_server.secret.hex())
        monkeypatch.setenv("HVDT_RANK", "1")
        monkeypatch.setenv("HVDT_SIZE", "4")
        ps = peer_store_mod.get_peer_store()
        assert ps is not None
        assert (ps.rank, ps.size, ps.watched_peer()) == (1, 4, 2)
        assert peer_store_mod.get_peer_store() is ps   # cached

    def test_jax_state_commit_and_peer_resume(self, kv_server, monkeypatch,
                                              tmp_path):
        """JaxState integration: commit publishes to the peer tier; a
        fresh state resumes from it (ties beat the disk tier) and
        records restored_from."""
        import horovod_tpu as hvd

        monkeypatch.setenv("HVDT_PEER_STORE", "1")
        monkeypatch.setenv("HVDT_RENDEZVOUS_ADDR", "127.0.0.1")
        monkeypatch.setenv("HVDT_RENDEZVOUS_PORT", str(kv_server.port))
        monkeypatch.setenv("HVDT_SECRET", kv_server.secret.hex())
        monkeypatch.setenv("HVDT_RANK", "0")
        monkeypatch.setenv("HVDT_SIZE", "1")
        peer_store_mod.reset()
        path = str(tmp_path / "state.pkl")

        class LocalState(hvd.elastic.JaxState):
            def sync(self):
                self.save()

        st = LocalState(path=path, w=np.zeros(2, np.float32), batch=0)
        assert st.restored_from is None
        st.w = st.w + 4.0
        st.batch = 6
        st.commit()
        st2 = LocalState(path=path, w=np.zeros(2, np.float32), batch=0)
        assert st2.restored_from == "peer"
        assert st2.batch == 6
        np.testing.assert_allclose(st2.w, 4.0)
        # Disk wins when it is strictly newer (peer publish lost).
        st2.batch = 9
        st2.save()
        st2.persist()
        st3 = LocalState(path=path, w=np.zeros(2, np.float32), batch=0)
        assert st3.restored_from == "disk"
        assert st3.batch == 9


# ---------------------------------------------------------------------------
# Deterministic data resume: sampler cursor + loader seek (satellite)
# ---------------------------------------------------------------------------

class TestSamplerCursor:
    def test_record_batch_advances_cursor(self):
        from horovod_tpu.data.sampler import ElasticSampler

        s = ElasticSampler(100, shuffle=False, rank=0, size=4)
        assert s.cursor() == {"epoch": 0, "batch_idx": 0}
        for i in range(3):
            s.record_batch(i, 8)
        assert s.cursor() == {"epoch": 0, "batch_idx": 3}
        assert s.state_dict()["batch_idx"] == 3
        s.set_epoch(1)
        assert s.cursor() == {"epoch": 1, "batch_idx": 0}

    def test_cursor_survives_shrink_grow_resize(self):
        """4 -> 2 -> 4: the cursor rides load_state_dict across world
        resizes and the remaining work repartitions each time."""
        from horovod_tpu.data.sampler import ElasticSampler

        s4 = ElasticSampler(96, shuffle=False, rank=0, size=4)
        for i in range(2):
            s4.record_batch(i, 8)        # 2 batches * 8 * 4 ranks = 64
        state = s4.state_dict()
        assert state == {"epoch": 0, "processed_num": 64, "batch_idx": 2}

        s2 = ElasticSampler(96, shuffle=False, rank=1, size=2)
        s2.load_state_dict(state)
        assert s2.cursor() == {"epoch": 0, "batch_idx": 2}
        assert len(s2.remaining_indices) == 96 - 64
        assert len(s2) == 16                       # 32 remaining / 2 ranks
        s2.record_batch(2, 8)                      # 64 + 8*2 = 80

        s4b = ElasticSampler(96, shuffle=False, rank=3, size=4)
        s4b.load_state_dict(s2.state_dict())
        assert s4b.cursor() == {"epoch": 0, "batch_idx": 3}
        assert len(s4b.remaining_indices) == 96 - 80
        assert len(s4b) == 4
        # Remaining indices are exactly the unprocessed tail.
        assert s4b.remaining_indices[0] == 80

    def test_pre_cursor_state_dict_accepted(self):
        from horovod_tpu.data.sampler import ElasticSampler

        s = ElasticSampler(10, shuffle=False, rank=0, size=1)
        s.load_state_dict({"epoch": 2, "processed_num": 4})
        assert s.cursor() == {"epoch": 2, "batch_idx": 0}


class TestLoaderSeek:
    def test_seek_skips_unprocessed(self):
        from horovod_tpu.data.loader import BaseDataLoader

        processed = []

        class Loader(BaseDataLoader):
            def __len__(self):
                return 8

            def _iterate(self):
                yield from range(8)

            def _process_batch(self, batch):
                processed.append(batch)
                return batch * 10

        ld = Loader()
        assert ld.seek({"epoch": 0, "batch_idx": 5}) is ld
        assert list(ld) == [50, 60, 70]
        # Skipped batches never hit _process_batch (no wasted decode /
        # device transfer on the replay window).
        assert processed == [5, 6, 7]

    def test_seek_forms_and_validation(self):
        from horovod_tpu.data.loader import AsyncDataLoader

        ld = AsyncDataLoader(list(range(4)), async_loader_queue_size=0)
        assert list(ld.seek((1, 2))) == [2, 3]
        assert list(ld.seek(3)) == [3]
        with pytest.raises(ValueError, match=">= 0"):
            ld.seek(-1)

    def test_async_reiteration_after_seek(self):
        """The satellite case: an AsyncDataLoaderMixin iterates after a
        seek (fast-forward through the producer queue), and the NEXT
        iteration is a fresh full epoch — seek is one-shot."""
        from horovod_tpu.data.loader import AsyncDataLoader

        ld = AsyncDataLoader(list(range(10)), async_loader_queue_size=4)
        ld.seek({"epoch": 0, "batch_idx": 6})
        assert list(ld) == [6, 7, 8, 9]
        assert list(ld) == list(range(10))
        ld.seek({"epoch": 0, "batch_idx": 9})
        assert list(ld) == [9]
        ld.close()

    def test_seek_past_end_yields_nothing(self):
        from horovod_tpu.data.loader import AsyncDataLoader

        ld = AsyncDataLoader(list(range(3)), async_loader_queue_size=2)
        ld.seek(7)
        assert list(ld) == []
        ld.close()

    def test_seek_charges_replay_phase(self, monkeypatch):
        from horovod_tpu.data.loader import AsyncDataLoader

        monkeypatch.setenv("HVDT_TELEMETRY", "1")
        step_stats.reset_recovery_ledger()
        ld = AsyncDataLoader(list(range(6)), async_loader_queue_size=0)
        ld.seek(4)
        assert list(ld) == [4, 5]
        ledger = step_stats.recovery_ledger()
        assert ledger.recovery_snapshot().get("replay", 0) >= 0
        assert "replay" in ledger.recovery_snapshot()


# ---------------------------------------------------------------------------
# CLI / knob wiring
# ---------------------------------------------------------------------------

class TestCliWiring:
    def test_goodput_flags_forward_as_env(self):
        from horovod_tpu.runner.launch import knob_env_for, parse_args

        args = parse_args(["--async-ckpt", "--peer-store",
                           "--ckpt-snapshot-budget-s", "2.5",
                           "-np", "2", "--", "python", "train.py"])
        env = knob_env_for(args)
        assert env["HVDT_ASYNC_CKPT"] == "1"
        assert env["HVDT_PEER_STORE"] == "1"
        assert env["HVDT_CKPT_SNAPSHOT_BUDGET_S"] == "2.5"

    def test_yaml_resilience_section(self, tmp_path):
        from horovod_tpu.runner.config_parser import (apply_config_file,
                                                      env_from_args)
        from horovod_tpu.runner.launch import parse_args

        cfg = os.path.join(str(tmp_path), "c.yaml")
        with open(cfg, "w") as f:
            f.write("resilience:\n  async_ckpt: true\n  peer_store: true\n")
        args = parse_args(["--config-file", cfg, "--", "python", "t.py"])
        file_values = apply_config_file(args, cfg)
        env = env_from_args(args, file_values, base_env={})
        assert env["HVDT_ASYNC_CKPT"] == "1"
        assert env["HVDT_PEER_STORE"] == "1"

    def test_goodput_knobs_registered(self):
        from horovod_tpu.common import config

        for name in ("HVDT_ASYNC_CKPT", "HVDT_PEER_STORE",
                     "HVDT_CKPT_SNAPSHOT_BUDGET_S"):
            assert name in config.KNOBS
        assert config.KNOBS["HVDT_CKPT_SNAPSHOT_BUDGET_S"].default == 1.0


# ---------------------------------------------------------------------------
# Multiprocess acceptance scenarios
# ---------------------------------------------------------------------------

def _records(log_path):
    """Parsed log lines of tests/data/goodput_main.py."""
    out = []
    with open(log_path) as f:
        for ln in f:
            parts = ln.split()
            if not parts:
                continue
            out.append(parts)
    return out


def _scenario_env(tmp_path, extra):
    env = dict(os.environ)
    env.pop("HVDT_TELEMETRY", None)
    env.update({
        "ELASTIC_TEST_LOG": os.path.join(tmp_path, "progress.log"),
        "ELASTIC_TEST_STATE": os.path.join(tmp_path, "state.pkl"),
        "GOODPUT_CKPT_DIR": os.path.join(tmp_path, "ckpts"),
        "ELASTIC_TEST_BATCHES": "16",
        "ELASTIC_TEST_SLEEP": "0.08",
        "ELASTIC_TEST_HB_TIMEOUT": "5",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
        "HVDT_ASYNC_CKPT": "1",
        "HVDT_PEER_STORE": "1",
        "HVDT_FAULT_JOURNAL": os.path.join(tmp_path, "fault_journal"),
        "HVDT_ELASTIC_BLACKLIST_COOLDOWN_S": "1",
    })
    env.update(extra)
    return env


def _run_scenario(tmp_path, env, discover_lines, port, min_np, max_np,
                  timeout=300):
    discover = os.path.join(str(tmp_path), "discover.sh")
    with open(discover, "w") as f:
        f.write("#!/bin/sh\n" + discover_lines + "\n")
    os.chmod(discover, 0o755)
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "--min-np", str(min_np), "--max-np", str(max_np),
         "--host-discovery-script", discover,
         "--coordinator-port", str(port),
         "--", sys.executable, os.path.join(REPO, "tests", "data",
                                            "goodput_main.py")],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        pytest.fail(f"goodput scenario hung:\n{out.decode()[-3000:]}")
    return proc.returncode, out.decode()


def _assert_goodput_invariants(records, text, total, budget_s=30.0,
                               killed_ranks=(1,), crash_batch=10):
    data = [(int(r[1]), int(r[3]), int(r[4]))
            for r in records if r[0] == "data"]
    restores = [(int(r[1]), r[2], int(r[3]), int(r[4]))
                for r in records if r[0] == "restore"]
    # Every restore after the kill came from the peer RAM tier — the
    # disk tier was never needed (hvdt_peer_restore_total > 0 rides the
    # restore record's counter column).
    assert restores, "no rank ever recorded a restore"
    assert all(tier == "peer" for _, tier, _, _ in restores), restores
    assert not any(tier == "disk" for _, tier, _, _ in restores)
    assert any(total_col > 0 for _, _, _, total_col in restores)
    # Committed batch ids are gap-free and replay-free per rank: each
    # bid processed at most twice overall (the at-most-one-uncommitted
    # batch a crash window may legitimately replay), every bid covered,
    # and the id stream never goes backwards by more than that window.
    by_rank = {}
    for rank, bid, ts in data:
        by_rank.setdefault(rank, []).append((ts, bid))
    for rank, rows in by_rank.items():
        bids = [b for _, b in sorted(rows)]
        assert sorted(set(bids)) == list(range(total)), (
            f"rank {rank} bid coverage broken: {bids}")
        from collections import Counter

        dupes = {b: c for b, c in Counter(bids).items() if c > 2}
        assert not dupes, f"rank {rank} replayed committed batches: {dupes}"
    # Recovery budget: kill -> first-new-committed-batch wall clock for
    # the killed rank stays under the 30 s SLO.
    for rank in killed_ranks:
        rows = sorted(by_rank[rank])
        pre = [ts for ts, b in rows if b == crash_batch - 1]
        post = [ts for ts, b in rows if b == crash_batch]
        assert pre and post, f"rank {rank} never crossed the crash point"
        recovery_s = (min(post) - min(pre)) / 1000.0
        assert recovery_s < budget_s, (
            f"rank {rank} recovery took {recovery_s:.1f}s "
            f"(budget {budget_s}s)")
    # The async writer landed a verified LAST_GOOD under the launcher.
    ckpt = [int(r[2]) for r in records if r[0] == "ckpt"]
    assert ckpt and max(ckpt) >= 5, f"async checkpoint never landed: {ckpt}"
    # Loss continuity: every batch applied exactly once across the kill.
    assert f"final: batches={total} w0={total / 10:.1f}" in text


def test_kill_rank1_recovers_from_peer_ram_within_budget(tmp_path):
    """Acceptance scenario 1: crash@step=10:rank=1 under
    HVDT_ASYNC_CKPT=1 + HVDT_PEER_STORE=1 — recovery restores both
    ranks from the peer RAM tier (zero disk restores), inside the 30 s
    budget, with gap-free replay-free committed batches."""
    env = _scenario_env(str(tmp_path), {
        "HVDT_FAULT_PLAN": "crash@step=10:rank=1",
    })
    rc, text = _run_scenario(tmp_path, env, "echo localhost:2",
                             port=29791, min_np=2, max_np=2)
    assert rc == 0, text[-3000:]
    records = _records(env["ELASTIC_TEST_LOG"])
    _assert_goodput_invariants(records, text, total=16)
    # The driver attributes the rendezvous leg of the recovery budget.
    assert "rendezvous took" in text


@pytest.mark.slow
def test_pod_kill_recovers_from_peer_ram(tmp_path):
    """Acceptance scenario 2 (pod variant): pod_crash@step=10:pod=podB
    kills both ranks of pod B; every respawned rank restores from the
    peer RAM tier and the committed batch stream stays gap-free.

    Marked ``slow``: the rank-kill scenario above covers the same
    goodput machinery inside tier-1's 870 s budget; this whole-pod leg
    runs in the pre-merge smoke service (docker-compose test-smoke /
    ci/gen-matrix.sh --smoke), which carries no ``-m 'not slow'``
    filter."""
    env = _scenario_env(str(tmp_path), {
        "HVDT_FAULT_PLAN": "pod_crash@step=10:pod=podB",
        "ELASTIC_TEST_SLEEP": "0.1",
    })
    rc, text = _run_scenario(
        tmp_path, env, "echo localhost:2@podA\necho 127.0.0.1:2@podB",
        port=29796, min_np=2, max_np=4, timeout=360)
    assert rc == 0, text[-3000:]
    records = _records(env["ELASTIC_TEST_LOG"])
    _assert_goodput_invariants(records, text, total=16,
                               killed_ranks=(2, 3))
    # The two pod-B exits collapsed into ONE pod-removal event.
    assert text.count("pod-removal event for pod podB") == 1
