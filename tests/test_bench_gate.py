"""Bench fallback headline contract (VERDICT r4 weak #4).

When the accelerator is unavailable but a dated last-good TPU measurement
exists, ``bench.py``'s single JSON line must carry the cached TPU number as
the top-level ``value``/``vs_baseline`` — marked ``stale: true`` with an
``age_hours`` field — and keep the live CPU probe only as a sub-record.
A consumer reading only ``value`` must never conclude a 200x slowdown from
an outage (the round-4 ``value: 0.48`` footgun).
"""

import importlib.util
import json
import os
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def bench(monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(time, "sleep", lambda _s: None)
    monkeypatch.setenv("HVDT_BENCH_ATTEMPT_TIMEOUTS", "1")
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    return mod


LAST_GOOD = {
    "metric": "resnet50_images_per_sec_per_chip",
    "value": 2693.7, "unit": "images/sec/chip", "vs_baseline": 26.013,
    "platform": "tpu", "device_kind": "TPU v5 lite", "mfu": 0.3269,
    "batch_size": 128,
    "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                 time.gmtime(time.time() - 7200)),
}

CPU_PROBE = json.dumps({
    "metric": "resnet50_images_per_sec_per_chip", "value": 0.48,
    "unit": "images/sec/chip", "vs_baseline": 0.005, "platform": "cpu",
    "device_kind": "cpu", "mfu": None, "batch_size": 8,
})


def _run_main(bench, capsys, spawn, last_good):
    bench._spawn = spawn
    bench._load_last_good = lambda: last_good
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, "bench must print exactly one JSON line"
    return json.loads(out[0])


def test_fallback_promotes_last_good_headline(bench, capsys):
    def spawn(child_args, timeout_s, cpu_only=False):
        if cpu_only:
            return True, CPU_PROBE, ""
        return False, None, "chip down"

    d = _run_main(bench, capsys, spawn, dict(LAST_GOOD))
    assert d["value"] == 2693.7
    assert d["vs_baseline"] == 26.013
    assert d["platform"] == "tpu"
    assert d["stale"] is True
    assert d["age_hours"] == pytest.approx(2.0, abs=0.2)
    assert d["fallback_probe"]["platform"] == "cpu"
    assert d["fallback_probe"]["value"] == 0.48
    assert "accelerator unavailable" in d["error"]


def test_fallback_without_cache_keeps_cpu_probe(bench, capsys):
    def spawn(child_args, timeout_s, cpu_only=False):
        if cpu_only:
            return True, CPU_PROBE, ""
        return False, None, "chip down"

    d = _run_main(bench, capsys, spawn, None)
    assert d["platform"] == "cpu"
    assert d["value"] == 0.48
    assert "stale" not in d


def test_total_failure_still_one_line(bench, capsys):
    d = _run_main(bench, capsys,
                  lambda *a, **k: (False, None, "nope"), None)
    assert d["value"] == 0.0
    assert d["platform"] is None


def test_healthy_run_unchanged(bench, capsys, tmp_path):
    tpu_line = json.dumps({**LAST_GOOD, "measured_at": None})
    bench.LAST_GOOD_PATH = str(tmp_path / "lg.json")
    d = _run_main(bench, capsys,
                  lambda *a, **k: (True, tpu_line, ""), None)
    assert d["value"] == 2693.7
    assert "stale" not in d
    assert os.path.exists(bench.LAST_GOOD_PATH)


def test_no_cache_env_protects_headline_cache(bench, capsys, tmp_path,
                                              monkeypatch):
    """Experimental-config A/B legs (HVDT_BENCH_NO_CACHE=1, e.g. the
    fused-conv bench) must not overwrite the stock-config last-good."""
    tpu_line = json.dumps({**LAST_GOOD, "measured_at": None})
    bench.LAST_GOOD_PATH = str(tmp_path / "lg.json")
    monkeypatch.setenv("HVDT_BENCH_NO_CACHE", "1")
    d = _run_main(bench, capsys,
                  lambda *a, **k: (True, tpu_line, ""), None)
    assert d["value"] == 2693.7                  # result still printed
    assert not os.path.exists(bench.LAST_GOOD_PATH)
