"""MXNet interop binding tests (ref analogs: test/parallel/base_test_mxnet.py
API cases; horovod/mxnet/__init__.py DistributedOptimizer/Trainer).

mxnet is not in this image, so the binding's framework boundary is
exercised through a minimal stub that implements exactly the NDArray /
optimizer / gluon.Trainer surface the binding touches (``asnumpy``,
slice assignment, ``astype``, ``rescale_grad``, ``_params``/``_scale``).
The collective path underneath is the real eager controller.
"""

import sys
import types

import numpy as np
import pytest


class _NDArray:
    def __init__(self, arr, dtype=None):
        self._a = np.array(arr, dtype=dtype)

    def asnumpy(self):
        return self._a.copy()

    @property
    def dtype(self):
        return self._a.dtype

    @property
    def shape(self):
        return self._a.shape

    def astype(self, dt):
        return _NDArray(self._a.astype(dt))

    def __setitem__(self, key, value):
        self._a[key] = value._a if isinstance(value, _NDArray) else value


class _Optimizer:
    def __init__(self, learning_rate=0.1):
        self.lr = learning_rate
        self.rescale_grad = 1.0
        self.updated = []

    def update(self, index, weight, grad, state):
        self.updated.append(index)
        weight[:] = weight.asnumpy() - self.lr * self.rescale_grad * \
            grad.asnumpy()

    def update_multi_precision(self, index, weight, grad, state):
        self.update(index, weight, grad, state)

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        return None

    def set_learning_rate(self, lr):
        self.lr = lr


class _Parameter:
    def __init__(self, name, value):
        self.name = name
        self.grad_req = "write"
        self._data = _NDArray(value)
        self._grad = _NDArray(np.ones_like(np.asarray(value)))

    def data(self):
        return self._data

    def list_grad(self):
        return [self._grad]


class _Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore=None):
        if isinstance(params, dict):
            params = list(params.values())
        self._params = list(params)
        self._optimizer = optimizer
        self._scale = 1.0


@pytest.fixture()
def mx_stub(monkeypatch):
    mx = types.ModuleType("mxnet")
    mx.nd = types.SimpleNamespace(
        NDArray=_NDArray, array=lambda a, dtype=None: _NDArray(a, dtype))
    mx.optimizer = types.SimpleNamespace(Optimizer=_Optimizer)
    mx.gluon = types.SimpleNamespace(Trainer=_Trainer)
    monkeypatch.setitem(sys.modules, "mxnet", mx)
    from horovod_tpu.interop import mxnet as binding

    binding._CLS_CACHE.clear()
    yield mx
    binding._CLS_CACHE.clear()


class TestMxnetOps:
    def test_allreduce_roundtrip(self, hvd, mx_stub):
        from horovod_tpu.interop import mxnet as hmx

        t = _NDArray([1.0, 2.0, 3.0])
        out = hmx.allreduce(t, name="mx0")
        assert isinstance(out, _NDArray)
        np.testing.assert_allclose(out.asnumpy(), [1.0, 2.0, 3.0])

    def test_allreduce_inplace_prescale(self, hvd, mx_stub):
        from horovod_tpu.interop import mxnet as hmx

        t = _NDArray([2.0, 4.0])
        out = hmx.allreduce_(t, average=False, name="mx1",
                             prescale_factor=0.5)
        assert out is t
        np.testing.assert_allclose(t.asnumpy(), [1.0, 2.0])

    def test_grouped_inplace(self, hvd, mx_stub):
        from horovod_tpu.interop import mxnet as hmx

        ts = [_NDArray([1.0]), _NDArray([2.0, 3.0])]
        outs = hmx.grouped_allreduce_(ts, average=False, name="mxg")
        assert outs[0] is ts[0]
        np.testing.assert_allclose(ts[1].asnumpy(), [2.0, 3.0])

    def test_broadcast_and_allgather(self, hvd, mx_stub):
        from horovod_tpu.interop import mxnet as hmx

        t = _NDArray([5.0, 6.0])
        np.testing.assert_allclose(
            hmx.broadcast(t, root_rank=0, name="mxb").asnumpy(), [5.0, 6.0])
        np.testing.assert_allclose(
            hmx.allgather(t, name="mxag").asnumpy(), [5.0, 6.0])

    def test_alltoall(self, hvd, mx_stub):
        from horovod_tpu.interop import mxnet as hmx

        t = _NDArray([7.0, 8.0])
        out, splits = hmx.alltoall(t, name="mxa2a")
        np.testing.assert_allclose(out.asnumpy(), [7.0, 8.0])
        assert splits == [2]

    def test_broadcast_parameters_dict(self, hvd, mx_stub):
        from horovod_tpu.interop import mxnet as hmx

        params = {"w": _Parameter("w", [1.0, 2.0]),
                  "b": _NDArray([3.0])}
        hmx.broadcast_parameters(params, root_rank=0)
        np.testing.assert_allclose(params["w"].data().asnumpy(), [1.0, 2.0])
        np.testing.assert_allclose(params["b"].asnumpy(), [3.0])


class TestMxnetOptimizer:
    def test_rescale_and_delegation(self, hvd, mx_stub):
        from horovod_tpu.interop import mxnet as hmx

        base = _Optimizer(learning_rate=0.5)
        opt = hmx.DistributedOptimizer(base, gradient_predivide_factor=2.0)
        # rescale_grad normalized by predivide/size (size 1 here).
        assert base.rescale_grad == 2.0
        w, g = _NDArray([1.0]), _NDArray([1.0])
        opt.update(3, w, g, None)
        assert base.updated == [3]
        assert opt.lr == 0.5                     # __getattr__ delegation
        opt.set_learning_rate(0.1)
        assert base.lr == 0.1

    def test_do_allreduce_multirank_paths(self, hvd, mx_stub):
        """With a size-2 process set view the optimizer must enqueue real
        collectives (actual world is 1 rank, so sum == identity)."""
        from horovod_tpu.interop import mxnet as hmx
        from horovod_tpu.common.process_sets import global_process_set

        gps = global_process_set()
        ps = types.SimpleNamespace(id=gps.id, size=lambda: 2,
                                   included=lambda: True)
        base = _Optimizer()
        opt = hmx.DistributedOptimizer(base, process_set=ps)
        g1, g2 = _NDArray([2.0]), _NDArray([4.0])
        opt._do_allreduce([0, 1], [g1, g2])      # per-index path
        np.testing.assert_allclose(g1.asnumpy(), [2.0])
        opt2 = hmx.DistributedOptimizer(_Optimizer(), num_groups=1,
                                        process_set=ps)
        opt2._do_allreduce([0, 1], [g1, g2])     # grouped path
        np.testing.assert_allclose(g2.asnumpy(), [4.0])


class TestMxnetTrainer:
    def _params(self):
        return {"a": _Parameter("a", [1.0, 1.0]),
                "b": _Parameter("b", [2.0])}

    def test_scale_and_unwrap(self, hvd, mx_stub):
        from horovod_tpu.interop import mxnet as hmx

        base = _Optimizer()
        with pytest.warns(UserWarning, match="unwrapped"):
            tr = hmx.DistributedTrainer(
                self._params(), hmx.DistributedOptimizer(base),
                gradient_predivide_factor=4.0)
        assert tr._optimizer is base
        assert tr._scale == 4.0                  # predivide/size(=1)

    def test_allreduce_grads_size1_noop(self, hvd, mx_stub):
        from horovod_tpu.interop import mxnet as hmx

        tr = hmx.DistributedTrainer(self._params(), _Optimizer())
        tr._allreduce_grads()                    # early-out, no enqueue

    def test_allreduce_grads_multirank(self, hvd, mx_stub):
        from horovod_tpu.interop import mxnet as hmx
        from horovod_tpu.common.process_sets import global_process_set

        gps = global_process_set()
        ps = types.SimpleNamespace(id=gps.id, size=lambda: 2,
                                   included=lambda: True)
        tr = hmx.DistributedTrainer(self._params(), _Optimizer(),
                                    process_set=ps, prefix="t0.")
        tr._allreduce_grads()
        for p in tr._params:
            np.testing.assert_allclose(p.list_grad()[0].asnumpy(),
                                       np.ones(p.data().shape))

    def test_allreduce_grads_grouped_compressed(self, hvd, mx_stub):
        from horovod_tpu.interop import mxnet as hmx
        from horovod_tpu.common.process_sets import global_process_set

        gps = global_process_set()
        ps = types.SimpleNamespace(id=gps.id, size=lambda: 2,
                                   included=lambda: True)
        tr = hmx.DistributedTrainer(self._params(), _Optimizer(),
                                    process_set=ps, num_groups=1,
                                    compression=hmx.Compression.fp16,
                                    prefix="t1.")
        tr._allreduce_grads()
        for p in tr._params:
            np.testing.assert_allclose(p.list_grad()[0].asnumpy(),
                                       np.ones(p.data().shape))


def test_core_names_resolve_on_bindings(hvd, mx_stub):
    """Drop-in parity: every reference framework module re-exports the
    core API (init/rank/size/predicates); the interop bindings must too."""
    from horovod_tpu.interop import CORE_NAMES
    from horovod_tpu.interop import mxnet as hmx
    from horovod_tpu.interop import tf as htf
    from horovod_tpu.interop import torch as htorch

    for mod in (hmx, htf, htorch):
        for nm in CORE_NAMES:
            assert getattr(mod, nm) is not None, (mod.__name__, nm)
    assert htorch.rank() == 0 and htf.size() >= 1
    assert hmx.mpi_built() is False and htf.xla_built() is True
