"""Static topology cost model + perf-regression gate
(horovod_tpu/analysis/costmodel.py, topology.py, the `--perf` CLI gate,
autotune model pre-seeding, and the magic-peak-flops / stale-baseline
lint satellites)."""

import json
import os

import pytest

from horovod_tpu.analysis import costmodel as cm
from horovod_tpu.analysis import schedule as sched
from horovod_tpu.analysis import topology as tp
from horovod_tpu.analysis.__main__ import (_gate_lint,
                                           _reference_fingerprints,
                                           main as analysis_main)
from horovod_tpu.analysis.lint import MagicPeakFlopsRule, lint_source

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ev(index, op, axes, dtype="float32", count=1024, nbytes=4096,
        context=(), post_barrier=False, barriers_before=0):
    return sched.CollectiveEvent(
        index=index, op=op, axes=tuple(axes), dtype=dtype, count=count,
        nbytes=nbytes, context=tuple(context),
        post_barrier=post_barrier, barriers_before=barriers_before)


# ---------------------------------------------------------------------------
# topology + geometry
# ---------------------------------------------------------------------------


class TestTopology:
    def test_spec_tiers_and_total(self):
        topo = tp.TopologySpec(pods=16, chips_per_pod=16)
        assert topo.total_chips == 256
        assert topo.tier_size("dcn") == 16
        assert topo.tier_size("ici") == 16
        with pytest.raises(ValueError):
            topo.tier_size("nvlink")

    def test_spec_validates(self):
        with pytest.raises(ValueError):
            tp.TopologySpec(pods=0)

    def test_from_env_pod_contract(self, monkeypatch):
        monkeypatch.setenv("HVDT_NUM_PODS", "4")
        monkeypatch.setenv("HVDT_POD_SIZE", "8")
        topo = tp.TopologySpec.from_env()
        assert (topo.pods, topo.chips_per_pod) == (4, 8)
        monkeypatch.delenv("HVDT_NUM_PODS")
        monkeypatch.delenv("HVDT_POD_SIZE")
        assert tp.TopologySpec.from_env().pods == 1

    def test_classify_axis(self):
        assert tp.classify_axis("dcn", ("dcn", "ici")) == "dcn"
        assert tp.classify_axis("ici", ("dcn", "ici")) == "ici"
        # position convention: innermost = ici, outer = dcn
        assert tp.classify_axis("dp", ("dp",)) == "ici"
        assert tp.classify_axis("dp", ("dp", "tp")) == "dcn"

    def test_peak_flops_from_one_table(self):
        from horovod_tpu.telemetry.step_stats import peak_flops_for

        assert tp.chip_peak_flops("v5 lite") == peak_flops_for(
            "v5 lite")[0]
        assert tp.chip_peak_flops("unknown-device") is None


class TestGeometry:
    def test_ring_allreduce(self):
        hops, wf = cm.collective_geometry("psum", "ring", 8)
        assert hops == 14 and wf == pytest.approx(1.75)

    def test_tree_allreduce(self):
        hops, wf = cm.collective_geometry("psum", "tree", 8)
        assert hops == 6 and wf == 2.0

    def test_reduce_scatter_and_gather(self):
        for op in ("reduce_scatter", "all_gather", "all_to_all"):
            hops, wf = cm.collective_geometry(op, "ring", 4)
            assert hops == 3 and wf == pytest.approx(0.75)

    def test_single_member_group_free(self):
        assert cm.collective_geometry("psum", "ring", 1) == (0.0, 0.0)


# ---------------------------------------------------------------------------
# calibration: roundtrip, lookup chain, fitting
# ---------------------------------------------------------------------------


class TestCalibration:
    def test_roundtrip(self, tmp_path):
        cal = cm.Calibration(
            {("ici", "ring", "f32"): tp.LinkConstants(1e-6, 2e-9),
             ("dcn", "tree", "int8"): tp.LinkConstants(5e-6, 8e-9, 1e-10)},
            meta={"source": "unit"})
        p = str(tmp_path / "cal.json")
        cal.save(p)
        back = cm.load_calibration(p)
        assert back.groups == cal.groups
        assert back.meta["source"] == "unit"

    def test_missing_file_degrades(self, tmp_path):
        cal = cm.load_calibration(str(tmp_path / "nope.json"))
        assert cal.groups == {}
        assert "degraded" in cal.meta

    def test_lookup_fallback_chain(self):
        ring = tp.LinkConstants(1e-6, 2e-9)
        cal = cm.Calibration({("ici", "ring", "f32"): ring})
        assert cal.lookup("ici", "ring", "f32") is ring
        # wire falls back to the f32 sibling
        assert cal.lookup("ici", "ring", "bf16") is ring
        # unknown tier -> topology defaults, with the wire's gamma
        c = cal.lookup("dcn", "ring", "int8")
        assert c.beta_s_per_byte == pytest.approx(
            tp.DEFAULT_TIER_CONSTANTS["dcn"].beta_s_per_byte
            * cm.wire_shrink("int8"))
        assert c.gamma_s_per_byte > 0

    def test_env_path_override(self, tmp_path, monkeypatch):
        p = str(tmp_path / "alt.json")
        monkeypatch.setenv("HVDT_COSTMODEL_CALIBRATION", p)
        assert cm.default_calibration_path() == p
        monkeypatch.delenv("HVDT_COSTMODEL_CALIBRATION")
        assert cm.default_calibration_path().endswith(
            cm.CALIBRATION_NAME)


class TestFit:
    def _rows(self, alpha, beta, algorithm="ring", axis="ici",
              axis_size=4, wire="f32"):
        rows = []
        for size in (1 << 14, 1 << 16, 1 << 18, 1 << 20, 1 << 22):
            hops, wf = cm.collective_geometry("allreduce", algorithm,
                                              axis_size)
            wire_b = wf * size * cm.wire_shrink(wire)
            rows.append({"axis": axis, "algorithm": algorithm,
                         "wire": wire, "size_bytes": size,
                         "axis_size": axis_size,
                         "seconds": alpha * hops + beta * wire_b,
                         "bytes_on_wire": wire_b})
        return rows

    def test_recovers_known_constants(self):
        cal = cm.fit_from_bench(self._rows(alpha=5e-6, beta=3e-9))
        c = cal.groups[("ici", "ring", "f32")]
        assert c.alpha_s == pytest.approx(5e-6, rel=1e-6)
        assert c.beta_s_per_byte == pytest.approx(3e-9, rel=1e-6)

    def test_nonneg_clamp(self):
        # Constant-time rows regardless of size: pure latency; the
        # byte term must clamp to >= 0, never fit negative.
        rows = [{"axis": "dcn", "algorithm": "ring", "wire": "f32",
                 "size_bytes": s, "axis_size": 2, "seconds": 1e-3,
                 "bytes_on_wire": None}
                for s in (1 << 12, 1 << 16, 1 << 20)]
        cal = cm.fit_from_bench(rows)
        c = cal.groups[("dcn", "ring", "f32")]
        assert c.alpha_s >= 0 and c.beta_s_per_byte >= 0

    def test_single_row_group_skipped(self):
        cal = cm.fit_from_bench(self._rows(1e-6, 1e-9)[:1])
        assert cal.groups == {}

    def test_normalize_rows_legacy_and_compound_wire(self):
        doc = {"n_devices": 8, "mesh": {"dcn": 2, "ici": 4}, "rows": [
            {"axis": "ici", "algorithm": "ring", "wire": "f32",
             "bytes": 4096, "us": 100.0},
            {"axis": "ici+dcn", "algorithm": "hierarchical",
             "wire": "f32/f32", "bytes": 4096, "us": 50.0},
            {"axis": "ici+dcn", "algorithm": "hierarchical",
             "wire": "f32/int8", "bytes": 4096, "us": 40.0},
            {"axis": "", "bytes": 1, "us": 1.0},        # no axis: drop
            {"axis": "dp", "us": 1.0},                   # no size: drop
        ]}
        rows = cm.normalize_rows(doc)
        assert len(rows) == 3
        assert rows[0]["seconds"] == pytest.approx(1e-4)
        assert rows[0]["axis_size"] == 4
        wires = {r["wire"] for r in rows}
        # homogeneous compound collapses; mixed stays distinct
        assert wires == {"f32", "f32/int8"}

    def test_checked_in_calibration_is_fitted(self):
        cal = cm.load_calibration(
            os.path.join(REPO, cm.CALIBRATION_NAME))
        assert "degraded" not in cal.meta
        assert ("ici", "ring", "f32") in cal.groups
        assert ("dcn", "ring", "f32") in cal.groups
        assert ("ici+dcn", "flat", "f32") in cal.groups
        meas = cal.meta.get("measured_hier_speedup")
        assert meas and meas["value"] > 0 and meas["at_bytes"] > 0


# ---------------------------------------------------------------------------
# fingerprint evaluation: hidden/exposed, wire accounting
# ---------------------------------------------------------------------------


class TestEvaluate:
    def _model(self):
        return cm.CostModel(cm.Calibration())    # topology defaults

    def test_barrier_groups_split_hidden_vs_exposed(self):
        fp = sched.ScheduleFingerprint([
            _ev(0, "psum", ("ici",), barriers_before=0),
            _ev(1, "psum", ("ici",), post_barrier=True,
                barriers_before=1),
            _ev(2, "psum", ("ici",), post_barrier=True,
                barriers_before=2),
        ], n_barriers=2, label="pipe")
        fc = self._model().evaluate(
            fp, tp.TopologySpec(pods=1, chips_per_pod=4))
        # last barrier group is exposed; earlier buckets hide
        assert len(fc.per_bucket_s) == 3
        assert fc.exposed_comm_s == pytest.approx(fc.per_bucket_s[2])
        assert fc.hidden_comm_s == pytest.approx(
            fc.per_bucket_s[0] + fc.per_bucket_s[1])
        assert 0 < fc.overlap_fraction < 1

    def test_no_barriers_all_exposed(self):
        fp = sched.ScheduleFingerprint(
            [_ev(0, "psum", ("ici",)), _ev(1, "psum", ("ici",))],
            n_barriers=0, label="mono")
        fc = self._model().evaluate(
            fp, tp.TopologySpec(pods=1, chips_per_pod=4))
        assert fc.exposed_comm_s == pytest.approx(fc.total_comm_s)
        assert fc.overlap_fraction == 0.0

    def test_wire_accounting_ring(self):
        fp = sched.ScheduleFingerprint(
            [_ev(0, "psum", ("ici",), nbytes=8192, count=2048)])
        fc = self._model().evaluate(
            fp, tp.TopologySpec(pods=1, chips_per_pod=8))
        # ring allreduce moves 2(n-1)/n of the payload
        assert fc.wire_bytes_by_axis["ici"] == int(8192 * 1.75)

    def test_flat_multi_tier_pays_both_tiers(self):
        fp = sched.ScheduleFingerprint(
            [_ev(0, "psum", ("dcn", "ici"), nbytes=8192, count=2048)])
        fc = self._model().evaluate(
            fp, tp.TopologySpec(pods=2, chips_per_pod=4))
        assert set(fc.wire_bytes_by_axis) == {"ici", "dcn"}
        # full payload on the slow tier too — the flat penalty
        assert fc.wire_bytes_by_axis["dcn"] == 8192  # 2(2-1)/2 * 8192

    def test_int8_event_uses_wire_class(self):
        fp8 = sched.ScheduleFingerprint(
            [_ev(0, "all_to_all", ("dcn",), dtype="int8",
                 nbytes=1024, count=1024)])
        fc = self._model().evaluate(
            fp8, tp.TopologySpec(pods=4, chips_per_pod=1))
        assert fc.total_comm_s > 0
        # nbytes are wire bytes already; the tier total reflects them
        assert fc.wire_bytes_by_axis["dcn"] == int(1024 * 0.75)

    def test_evaluation_deterministic(self):
        fp = sched.ScheduleFingerprint(
            [_ev(0, "psum", ("dcn", "ici")),
             _ev(1, "reduce_scatter", ("ici",), barriers_before=1,
                 post_barrier=True)], n_barriers=1)
        m = self._model()
        topo = tp.TopologySpec(pods=2, chips_per_pod=4)
        a = m.evaluate(fp, topo).to_dict()
        b = m.evaluate(fp, topo).to_dict()
        assert a == b


# ---------------------------------------------------------------------------
# model-vs-measured + weak scaling (the acceptance asserts)
# ---------------------------------------------------------------------------


class TestModelValidation:
    def test_hier_speedup_matches_measured_within_25pct(self):
        """The fitted model must reproduce the cached measured
        hierarchical_speedup_vs_flat_at_peak of the calibration
        sweep."""
        cal = cm.load_calibration(
            os.path.join(REPO, cm.CALIBRATION_NAME))
        meas = cal.meta["measured_hier_speedup"]
        mesh = meas["mesh"]
        model = cm.CostModel(cal)
        pred = model.hierarchical_speedup(
            meas["at_bytes"],
            tp.TopologySpec(pods=mesh["dcn"],
                            chips_per_pod=mesh["ici"]))
        assert abs(pred - meas["value"]) / meas["value"] <= 0.25

    def test_weak_scaling_monotone_and_deterministic(self):
        cal = cm.load_calibration(
            os.path.join(REPO, cm.CALIBRATION_NAME))
        model = cm.CostModel(cal)
        wl = tp.REFERENCE_STEP_WORKLOAD
        a = model.weak_scaling_curve(wl["grad_bytes"],
                                     wl["flops_per_step"])
        b = model.weak_scaling_curve(wl["grad_bytes"],
                                     wl["flops_per_step"])
        assert a == b                      # pure arithmetic, no devices
        chips = [r["chips"] for r in a]
        assert chips == list(cm.DEFAULT_CURVE_CHIPS)
        frs = [r["comm_fraction"] for r in a]
        assert all(later >= earlier
                   for earlier, later in zip(frs, frs[1:]))
        assert all(r["comm_s"] > 0 for r in a)

    def test_curve_comm_grows_with_pods(self):
        model = cm.CostModel(cm.Calibration())
        rows = model.weak_scaling_curve(1 << 26, 1e9)
        comm = [r["comm_s"] for r in rows]
        assert comm == sorted(comm)
        assert rows[-1]["pods"] == 64 and rows[-1]["chips_per_pod"] == 4

    def test_256_chip_topology_evaluable_without_devices(self):
        """The point of ROADMAP 5(b): a 16x16 mesh priced on CPU."""
        fp = sched.ScheduleFingerprint(
            [_ev(0, "psum", ("dcn", "ici"), nbytes=1 << 20)])
        fc = cm.CostModel(cm.Calibration()).evaluate(
            fp, tp.TopologySpec(pods=16, chips_per_pod=16))
        assert fc.topology.total_chips == 256
        assert fc.total_comm_s > 0


# ---------------------------------------------------------------------------
# the --perf CI gate
# ---------------------------------------------------------------------------


class TestPerfGate:
    def test_repo_gate_clean(self, capsys):
        rc = analysis_main(["--perf"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "hier-speedup model" in out
        assert "weak-scaling comm fraction" in out

    def _export_reference(self, tmp_path, label="overlap-hier"):
        fps = {fp.label: fp for fp in _reference_fingerprints()}
        doc = fps[label].to_dict()
        path = tmp_path / f"{label}.json"
        path.write_text(json.dumps(doc))
        return doc, path

    def test_clean_fingerprint_roundtrip_passes(self, tmp_path,
                                                capsys):
        _, path = self._export_reference(tmp_path)
        rc = analysis_main(["--perf", "--perf-fingerprint", str(path)])
        assert rc == 0, capsys.readouterr().out

    def test_doubled_dcn_wire_bytes_fails_named(self, tmp_path,
                                                capsys):
        doc, _ = self._export_reference(tmp_path)
        for e in doc["events"]:
            if e["axes"] == ["dcn"]:
                e["nbytes"] *= 2
                e["count"] *= 2
        bad = tmp_path / "tampered.json"
        bad.write_text(json.dumps(doc))
        rc = analysis_main(["--perf", "--perf-fingerprint", str(bad)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "dcn wire bytes regression" in out
        assert "overlap-hier" in out

    def test_dropped_overlap_fails_named(self, tmp_path, capsys):
        doc, _ = self._export_reference(tmp_path)
        doc["n_barriers"] = 0
        for e in doc["events"]:
            e["post_barrier"] = False
            e["barriers_before"] = 0
        bad = tmp_path / "nooverlap.json"
        bad.write_text(json.dumps(doc))
        rc = analysis_main(["--perf", "--perf-fingerprint", str(bad)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "overlap fraction dropped" in out
        assert "exposed-comm regression" in out

    def test_update_baseline_roundtrip(self, tmp_path, capsys):
        bl = tmp_path / "perf.json"
        rc = analysis_main(["--perf", "--update-perf-baseline",
                            "--perf-baseline", str(bl)])
        assert rc == 0
        doc = json.loads(bl.read_text())
        assert set(doc["entries"]) == {
            "overlap-plain", "overlap-hier", "overlap-hier-zero",
            "parallel4d"}
        for entry in doc["entries"].values():
            assert entry["exposed_comm_s"] > 0
            assert entry["wire_bytes_by_axis"]
        rc = analysis_main(["--perf", "--perf-baseline", str(bl)])
        assert rc == 0, capsys.readouterr().out

    def test_missing_baseline_fails_with_hint(self, tmp_path, capsys):
        rc = analysis_main(["--perf", "--perf-baseline",
                            str(tmp_path / "nope.json")])
        out = capsys.readouterr().out
        assert rc == 1
        assert "--update-perf-baseline" in out

    def test_committed_baseline_current(self, capsys):
        """The checked-in .hvdt-perf-baseline.json matches what the
        reference fingerprints + calibration predict today — the
        ratchet is live, not stale."""
        rc = analysis_main(["--perf"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "FAIL" not in out


# ---------------------------------------------------------------------------
# lint satellites: magic-peak-flops + stale-baseline hard mode
# ---------------------------------------------------------------------------


class TestMagicPeakFlopsRule:
    def _lint(self, src, path="horovod_tpu/somewhere/mod.py"):
        return [f for f in lint_source(src, path,
                                       rules=[MagicPeakFlopsRule()])]

    def test_peak_literal_flagged(self):
        fs = self._lint("PEAK = 918e12\n")
        assert len(fs) == 1 and fs[0].rule == "magic-peak-flops"

    def test_bandwidth_literal_flagged(self):
        assert self._lint("BW = 819e9\n")

    def test_sentinels_and_conversions_pass(self):
        assert self._lint("x = -1e30\ny = 1e9\nz = s / 1e6\n") == []

    def test_blessed_homes_exempt(self):
        src = "PEAK = 918e12\n"
        assert self._lint(
            src, "horovod_tpu/telemetry/step_stats.py") == []
        assert self._lint(
            src, "horovod_tpu/analysis/topology.py") == []

    def test_repo_clean_under_rule(self):
        from horovod_tpu.analysis.lint import (default_paths,
                                               lint_paths)

        findings = [f for f in lint_paths(default_paths(REPO), root=REPO,
                                          rules=[MagicPeakFlopsRule()])]
        assert findings == [], [f.format() for f in findings]


class TestStaleBaselineHardMode:
    def _tree(self, tmp_path):
        pkg = tmp_path / "horovod_tpu"
        pkg.mkdir()
        (pkg / "mod.py").write_text(
            "import os\n"
            "def read():\n"
            "    return os.environ.get('HVDT_NOT_DECLARED_XYZ')\n")
        return str(tmp_path)

    def test_stale_entry_fails_hard_mode(self, tmp_path, capsys):
        from horovod_tpu.analysis.lint import run_lint

        root = self._tree(tmp_path)
        bl = str(tmp_path / ".hvdt-lint-baseline.json")
        # Baseline the real finding, then add a stale entry.
        _, found, _ = run_lint(root, baseline_path=bl,
                               update_baseline=True)
        doc = json.loads(open(bl).read())
        doc["suppressions"].append(
            {"key": "knob-drift:horovod_tpu/mod.py:deadbeef0000:0",
             "rule": "knob-drift", "reason": "edited away"})
        open(bl, "w").write(json.dumps(doc))
        assert _gate_lint(root, bl, update=False,
                          fail_on_stale=False) == 0
        rc = _gate_lint(root, bl, update=False, fail_on_stale=True)
        out = capsys.readouterr().out
        assert rc == 1
        assert "FAIL stale-baseline" in out

    def test_update_baseline_prunes_stale(self, tmp_path):
        from horovod_tpu.analysis.lint import load_baseline, run_lint

        root = self._tree(tmp_path)
        bl = str(tmp_path / ".hvdt-lint-baseline.json")
        run_lint(root, baseline_path=bl, update_baseline=True)
        doc = json.loads(open(bl).read())
        doc["suppressions"].append(
            {"key": "knob-drift:horovod_tpu/mod.py:deadbeef0000:0",
             "rule": "knob-drift", "reason": "stale"})
        open(bl, "w").write(json.dumps(doc))
        run_lint(root, baseline_path=bl, update_baseline=True)
        keys = set(load_baseline(bl))
        assert "knob-drift:horovod_tpu/mod.py:deadbeef0000:0" not in keys
        assert _gate_lint(root, bl, update=False,
                          fail_on_stale=True) == 0

    def test_lock_suppressions_not_counted_stale(self, tmp_path,
                                                 capsys):
        root = self._tree(tmp_path)
        bl = str(tmp_path / ".hvdt-lint-baseline.json")
        from horovod_tpu.analysis.lint import run_lint

        run_lint(root, baseline_path=bl, update_baseline=True)
        doc = json.loads(open(bl).read())
        doc["suppressions"].append(
            {"key": "lock-cycle:A->B->A", "rule": "lock-cycle",
             "reason": "keyed by the locks gate"})
        open(bl, "w").write(json.dumps(doc))
        assert _gate_lint(root, bl, update=False,
                          fail_on_stale=True) == 0


# ---------------------------------------------------------------------------
# autotune model pre-seeding
# ---------------------------------------------------------------------------


class TestAutotuneModelSeed:
    @pytest.fixture(autouse=True)
    def _clean_env(self, monkeypatch):
        for k in ("HVDT_AUTOTUNE_MODEL_SEED", "HVDT_TRANSPORT",
                  "HVDT_AUTOTUNE_TRANSPORT_SEED", "HVDT_OVERLAP",
                  "HVDT_QUANT", "HVDT_COMPRESSION", "HVDT_ZERO"):
            monkeypatch.delenv(k, raising=False)
        from horovod_tpu import transport
        from horovod_tpu.ops import overlap as ovl

        transport.reset()
        ovl.reset()
        yield
        transport.reset()
        ovl.reset()

    def test_off_by_default_no_behavior_change(self):
        from horovod_tpu.autotune import (_env_overlap, _env_quant_wire,
                                          _env_transport, _model_seed)

        assert _model_seed("transport") is None
        assert _env_transport() is False
        assert _env_overlap() is False
        assert _env_quant_wire() is False

    def test_model_orders_legs_when_enabled(self, monkeypatch):
        from horovod_tpu.autotune import (_env_overlap, _env_quant_wire,
                                          _env_transport)

        monkeypatch.setenv("HVDT_AUTOTUNE_MODEL_SEED", "1")
        expect = cm.predict_leg_order(cm.load_calibration(
            os.path.join(REPO, cm.CALIBRATION_NAME)))
        assert _env_transport() is expect["transport"]
        assert _env_overlap() is expect["overlap"]
        assert _env_quant_wire() is expect["quant"]

    def test_calibration_path_value(self, tmp_path, monkeypatch):
        from horovod_tpu.autotune import _model_seed

        # Craft a calibration where hierarchy clearly wins: slow dcn
        # links, cheap ici — the model must order transport=hier.
        cal = cm.Calibration({
            ("ici", "ring", "f32"): tp.LinkConstants(1e-7, 1e-11),
            ("dcn", "ring", "f32"): tp.LinkConstants(1e-6, 1e-8),
        })
        p = str(tmp_path / "cal.json")
        cal.save(p)
        monkeypatch.setenv("HVDT_AUTOTUNE_MODEL_SEED", p)
        assert _model_seed("transport") is True

    def test_measured_seed_wins_over_model(self, tmp_path,
                                           monkeypatch):
        from horovod_tpu.autotune import _env_transport

        monkeypatch.setenv("HVDT_AUTOTUNE_MODEL_SEED", "1")
        seed = tmp_path / "sweep.json"
        seed.write_text(json.dumps(
            {"hierarchical_speedup_vs_flat_at_peak": 1.4}))
        monkeypatch.setenv("HVDT_AUTOTUNE_TRANSPORT_SEED", str(seed))
        assert _env_transport() is True
        seed.write_text(json.dumps(
            {"hierarchical_speedup_vs_flat_at_peak": 0.6}))
        assert _env_transport() is False

    def test_unreadable_seed_falls_back_to_model(self, tmp_path,
                                                 monkeypatch):
        from horovod_tpu.autotune import _env_transport

        monkeypatch.setenv("HVDT_AUTOTUNE_TRANSPORT_SEED",
                           str(tmp_path / "missing.json"))
        assert _env_transport() is False     # model off: blind default
        monkeypatch.setenv("HVDT_AUTOTUNE_MODEL_SEED", "1")
        expect = cm.predict_leg_order(cm.load_calibration(
            os.path.join(REPO, cm.CALIBRATION_NAME)))
        assert _env_transport() is expect["transport"]

    def test_explicit_env_wins_over_model(self, monkeypatch):
        from horovod_tpu.autotune import _env_overlap, _env_quant_wire

        monkeypatch.setenv("HVDT_AUTOTUNE_MODEL_SEED", "1")
        monkeypatch.setenv("HVDT_OVERLAP", "off")
        assert _env_overlap() is False
        monkeypatch.setenv("HVDT_COMPRESSION", "bf16")
        assert _env_quant_wire() is False
        monkeypatch.setenv("HVDT_COMPRESSION", "int8")
        assert _env_quant_wire() is True

    def test_predict_leg_order_shape(self):
        verdict = cm.predict_leg_order(cm.Calibration())
        assert set(verdict) == {"transport", "quant", "overlap",
                                "moe", "pipeline"}
        assert all(isinstance(v, bool) for v in verdict.values())
        # defaults: slow dcn, fast ici => hierarchy + overlap pay off
        assert verdict["transport"] is True
        assert verdict["overlap"] is True


# ---------------------------------------------------------------------------
# CLI subprocess (the compose `analysis` service contract)
# ---------------------------------------------------------------------------


@pytest.mark.integration
def test_cli_perf_gate_subprocess():
    """`python -m horovod_tpu.analysis --perf` exits 0 from a bare
    environment — the gate forces its own deterministic 8-device sim."""
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS",)}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.analysis", "--perf"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert "hvdt-perf: 0 problem(s)" in proc.stdout
    assert "hvdt-analysis: CLEAN" in proc.stdout
