"""TorchEstimator tests (ref analog: test_spark_torch.py fit/transform
contract).  Separate module from the keras estimator tests so torch-only
environments still run this coverage."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")


def _toy_regression(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    w = np.asarray([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    y = x @ w + 0.01 * rng.randn(n, 1).astype(np.float32)
    return x, y


class TestTorchEstimator:
    def _bits(self):
        torch = pytest.importorskip("torch")
        torch.manual_seed(2)
        model = torch.nn.Sequential(torch.nn.Linear(4, 8),
                                    torch.nn.ReLU(),
                                    torch.nn.Linear(8, 1))
        opt = torch.optim.Adam(model.parameters(), lr=0.05)
        return torch, model, opt

    def test_validation(self):
        from horovod_tpu.orchestrate import TorchEstimator

        with pytest.raises(ValueError, match="requires"):
            TorchEstimator()

    @pytest.mark.integration
    def test_fit_transform_two_workers(self, monkeypatch):
        torch, model, opt = self._bits()
        from horovod_tpu.orchestrate import TorchEstimator
        from horovod_tpu.orchestrate.executor import Executor

        captured = {}
        orig_run = Executor.run

        def spy(self, fn, args=(), kwargs=None, per_rank_args=None):
            res = orig_run(self, fn, args=args, kwargs=kwargs,
                           per_rank_args=per_rank_args)
            captured["results"] = res
            return res

        monkeypatch.setattr(Executor, "run", spy)
        x, y = _toy_regression(n=64, seed=7)
        est = TorchEstimator(model=model, optimizer=opt,
                             loss=torch.nn.MSELoss(), num_workers=2,
                             epochs=8, batch_size=16)
        out = est.fit(x, y)
        assert est.history_[-1]["loss"] < est.history_[0]["loss"]
        pred = out.transform(x)
        assert pred.shape == (len(x), 1)
        assert float(np.mean((pred - y) ** 2)) < 3.0
        res = captured["results"]
        assert [r["size"] for r in res] == [2, 2]
        assert res[0]["checksum"] == pytest.approx(res[1]["checksum"],
                                                   abs=1e-8)

    @pytest.mark.integration
    def test_param_groups_and_float64_targets(self):
        """Multi-group optimizers keep per-group hyperparameters in the
        workers (regression: defaults-only rebuild), and float64 numpy
        targets train against a float32 model without dtype crashes."""
        from horovod_tpu.orchestrate import TorchEstimator
        from horovod_tpu.orchestrate.torch_estimator import _torch_worker

        torch.manual_seed(3)
        model = torch.nn.Sequential(torch.nn.Linear(4, 4),
                                    torch.nn.Linear(4, 1))
        opt = torch.optim.SGD([
            {"params": model[0].parameters(), "lr": 0.0},
            {"params": model[1].parameters(), "lr": 0.05},
        ], lr=0.01)
        x, y64 = _toy_regression(n=16, seed=9)
        y64 = y64.astype(np.float64)
        est = TorchEstimator(model=model, optimizer=opt,
                             loss=torch.nn.MSELoss(), num_workers=1,
                             epochs=2, batch_size=8)
        w0_frozen = model[0].weight.detach().clone()
        w1_before = model[1].weight.detach().clone()
        out = est.fit(x, y64)
        # lr=0 group must not move; lr=0.05 group must train
        assert torch.allclose(out.model[0].weight, w0_frozen)
        assert not torch.allclose(out.model[1].weight, w1_before)


@pytest.mark.integration
def test_torch_fit_df_disk_cache(monkeypatch):
    """cache='disk' trains through the spill->stream path with bounded
    chunks (torch twin of JaxEstimator's out-of-core e2e).  Uses the
    shared spark stub from tests/test_spark.py."""
    import sys

    import test_spark as stubmod

    ctx = stubmod._StubContext(default_parallelism=1)
    mod = __import__("types").ModuleType("pyspark")
    mod.SparkContext = __import__("types").SimpleNamespace(
        _active_spark_context=ctx)
    mod.BarrierTaskContext = stubmod._BarrierTaskContext
    monkeypatch.setitem(sys.modules, "pyspark", mod)

    from horovod_tpu.orchestrate import TorchEstimator
    from horovod_tpu.orchestrate import spill as spill_mod

    cap = 16
    orig = spill_mod._rows_chunk_to_table
    chunks = []

    def capped(rows, label_col, feature_cols):
        chunks.append(len(rows))
        assert len(rows) <= cap
        return orig(rows, label_col, feature_cols)

    monkeypatch.setattr(spill_mod, "_rows_chunk_to_table", capped)

    rows = [{"x": float(i % 7), "label": 2.0 * (i % 7)} for i in range(96)]
    df = stubmod._StubDataFrame(rows, ["x", "label"], ctx)

    torch.manual_seed(5)
    model = torch.nn.Linear(1, 1, bias=False)
    opt = torch.optim.SGD(model.parameters(), lr=0.02)

    def loss(pred, y):
        return torch.nn.functional.mse_loss(pred[:, 0], y)

    est = TorchEstimator(model=model, optimizer=opt, loss=loss,
                         num_workers=1, epochs=8, batch_size=16,
                         cache="disk", rows_per_group=cap)
    out = est.fit(df.repartition(1))
    assert len(chunks) >= 96 // cap
    assert est.history_[-1]["loss"] < est.history_[0]["loss"]
    pred = out.predict(np.asarray([[2.0]], np.float32))
    assert abs(float(pred[0, 0]) - 4.0) < 1.0
