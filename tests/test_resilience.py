"""Chaos battery for the resilience subsystem (horovod_tpu/resilience):
fault-plan parsing and deterministic injection, the zero-overhead no-op
contract, backoff/retry, checkpoint manifest + last-good fallback,
preemption-safe shutdown, stall escalation, KV/rendezvous hardening, and
the multiprocess kill-one-worker elastic recovery scenario."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from horovod_tpu.resilience import faults as faults_mod
from horovod_tpu.resilience.escalation import (ABORT, RESET, WARN,
                                               EscalationPolicy, Escalator)
from horovod_tpu.resilience.faults import (FaultInjector, InjectedFault,
                                           corrupt_checkpoint_dir, parse_plan)
from horovod_tpu.resilience.preempt import (PREEMPT_EXIT_CODE, Preempted,
                                            PreemptionGuard)
from horovod_tpu.resilience.retry import Backoff, RetriesExhausted, retry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_ambient_fault_plan(monkeypatch):
    """Tests own the plan: clear any ambient env plan and reset the
    module cache around each test."""
    monkeypatch.delenv("HVDT_FAULT_PLAN", raising=False)
    faults_mod.configure(None)
    yield
    faults_mod.configure(None)


# ---------------------------------------------------------------------------
# Fault-plan grammar
# ---------------------------------------------------------------------------

class TestPlanParsing:
    def test_issue_example_plan(self):
        specs = parse_plan("crash@step=12:rank=1,hang@step=30:secs=20,"
                           "corrupt_ckpt@step=40,kv_drop@p=0.1")
        kinds = [(s.kind, s.point) for s in specs]
        assert kinds == [("crash", "step"), ("hang", "step"),
                         ("corrupt_ckpt", "checkpoint.save"),
                         ("kv_drop", "kv")]
        assert specs[0].step == 12 and specs[0].rank == 1
        assert specs[1].secs == 20.0
        assert specs[3].p == 0.1

    def test_step_faults_default_to_once(self):
        crash, drop = parse_plan("crash@step=3,kv_drop@p=0.5")
        assert crash.times == 1          # fire once, not every commit
        assert drop.times is None        # probabilistic: unlimited

    def test_point_override_and_times(self):
        (spec,) = parse_plan("exc@point=serve.reload:times=2")
        assert spec.point == "serve.reload" and spec.times == 2

    def test_malformed_entries_raise(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            parse_plan("meteor@step=1")
        with pytest.raises(ValueError, match="unknown key"):
            parse_plan("crash@sstep=1")
        with pytest.raises(ValueError, match="key=value"):
            parse_plan("crash@step")

    def test_empty_entries_skipped(self):
        assert parse_plan(" , ,") == []


# ---------------------------------------------------------------------------
# Zero-overhead no-op contract (acceptance: identity-object test)
# ---------------------------------------------------------------------------

class TestNoOpWhenUnset:
    def test_get_injector_is_none(self):
        assert faults_mod.get_injector() is None

    def test_instrument_returns_the_same_object(self):
        def hot_path():
            return 42

        assert faults_mod.instrument(hot_path, "step") is hot_path
        assert faults_mod.instrument(hot_path, "kv") is hot_path

    def test_instrument_wraps_only_with_a_plan(self, monkeypatch):
        monkeypatch.setenv("HVDT_FAULT_PLAN", "kv_drop@p=0.0")

        def hot_path():
            return 42

        wrapped = faults_mod.instrument(hot_path, "kv")
        assert wrapped is not hot_path
        assert wrapped.__wrapped__ is hot_path
        assert wrapped() == 42

    def test_env_cache_follows_plan_changes(self, monkeypatch):
        assert faults_mod.get_injector() is None
        monkeypatch.setenv("HVDT_FAULT_PLAN", "exc@step=1")
        inj = faults_mod.get_injector()
        assert inj is not None and inj.active
        monkeypatch.delenv("HVDT_FAULT_PLAN")
        assert faults_mod.get_injector() is None

    def test_elastic_commit_unchanged_without_plan(self, monkeypatch):
        """State.commit's resilience hook must do literally nothing when
        no plan and no guard exist (the hot-path contract)."""
        import horovod_tpu.elastic as elastic

        state = elastic.ObjectState(batch=7)
        fired = []
        monkeypatch.setattr(
            state, "check_host_updates", lambda: fired.append(True))
        state.commit()
        assert fired == [True]


# ---------------------------------------------------------------------------
# Injector semantics
# ---------------------------------------------------------------------------

class TestInjectorSemantics:
    def test_exc_fires_at_first_step_past_threshold_once(self):
        inj = FaultInjector(parse_plan("exc@step=5"))
        inj.fire("step", step=4)                       # below: no fire
        with pytest.raises(InjectedFault):
            inj.fire("step", step=6)                   # >= threshold
        inj.fire("step", step=7)                       # once-only
        assert inj.counters == {"exc": 1}

    def test_injected_fault_is_a_horovod_internal_error(self):
        from horovod_tpu.common.exceptions import HorovodInternalError

        assert issubclass(InjectedFault, HorovodInternalError)

    def test_rank_filter(self):
        inj = FaultInjector(parse_plan("exc@step=1:rank=1"))
        inj.fire("step", step=5, rank=0)               # wrong rank
        with pytest.raises(InjectedFault):
            inj.fire("step", step=5, rank=1)

    def test_probabilistic_faults_are_deterministic_under_seed(self):
        def draw(seed):
            inj = FaultInjector(parse_plan("kv_drop@p=0.3"), seed=seed)
            hits = []
            for i in range(50):
                try:
                    inj.fire("kv")
                    hits.append(0)
                except ConnectionError:
                    hits.append(1)
            return hits

        assert draw(7) == draw(7)
        assert draw(7) != draw(8)

    def test_crash_and_hang_actions(self):
        exits, sleeps = [], []
        inj = FaultInjector(parse_plan("crash@step=2:code=9,"
                                       "hang@step=4:secs=0.5"),
                            sleep_fn=sleeps.append, exit_fn=exits.append)
        inj.fire("step", step=2)
        assert exits == [9]
        inj.fire("step", step=4)
        assert sleeps == [0.5]

    def test_wrong_point_never_fires(self):
        inj = FaultInjector(parse_plan("exc@step=1"))
        inj.fire("kv", step=99)
        inj.fire("checkpoint.save", step=99)
        assert inj.fired_total() == 0


# ---------------------------------------------------------------------------
# Backoff / retry primitive
# ---------------------------------------------------------------------------

class TestBackoff:
    def test_exponential_growth_capped(self):
        b = Backoff(first=0.1, factor=2.0, cap=0.4, jitter=0.0,
                    sleep_fn=lambda s: None)
        assert [b.next_delay() for _ in range(4)] == [0.1, 0.2, 0.4, 0.4]

    def test_jitter_stays_within_band(self):
        import random

        b = Backoff(first=1.0, factor=1.0, cap=1.0, jitter=0.5,
                    rng=random.Random(0), sleep_fn=lambda s: None)
        for _ in range(100):
            d = b.next_delay()
            assert 0.5 <= d <= 1.0

    def test_deadline_bounds_total_sleep(self):
        slept = []
        clock = [0.0]

        def fake_sleep(s):
            slept.append(s)
            clock[0] += s

        b = Backoff(first=0.1, cap=10.0, jitter=0.0, deadline_s=1.0,
                    sleep_fn=fake_sleep, clock=lambda: clock[0])
        while b.sleep():
            pass
        assert sum(slept) <= 1.0 + 1e-9
        assert not b.sleep()      # stays exhausted

    def test_reset_rewinds_the_ladder(self):
        b = Backoff(first=0.1, factor=2.0, cap=10.0, jitter=0.0)
        b.next_delay(), b.next_delay()
        b.reset()
        assert b.next_delay() == 0.1

    def test_rejects_nonsense(self):
        with pytest.raises(ValueError):
            Backoff(first=0.0)
        with pytest.raises(ValueError):
            Backoff(first=1.0, cap=0.5)


class TestRetry:
    def test_succeeds_after_transient_failures(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("transient")
            return "ok"

        assert retry(flaky, attempts=5,
                     backoff=Backoff(first=0.001, cap=0.002)) == "ok"
        assert len(calls) == 3

    def test_exhaustion_raises_with_cause(self):
        def dead():
            raise ConnectionError("still down")

        with pytest.raises(RetriesExhausted) as ei:
            retry(dead, attempts=3, backoff=Backoff(first=0.001, cap=0.002))
        assert isinstance(ei.value.__cause__, ConnectionError)

    def test_non_retryable_propagates_immediately(self):
        calls = []

        def fatal():
            calls.append(1)
            raise ValueError("a 403 is not a flake")

        with pytest.raises(ValueError):
            retry(fatal, attempts=5, backoff=Backoff(first=0.001, cap=0.002))
        assert len(calls) == 1

    def test_unbounded_retry_rejected(self):
        with pytest.raises(ValueError, match="attempts"):
            retry(lambda: 1)


# ---------------------------------------------------------------------------
# Checkpoint hardening: manifest, LAST_GOOD, corrupt fallback
# ---------------------------------------------------------------------------

class TestCheckpointHardening:
    def _mgr(self, tmp_path, **kw):
        from horovod_tpu.checkpoint import CheckpointManager

        kw.setdefault("max_to_keep", 10)
        return CheckpointManager(os.path.join(tmp_path, "ckpts"), **kw)

    def test_save_writes_manifest_and_last_good(self, hvd, tmp_path):
        mgr = self._mgr(tmp_path)
        mgr.save(5, {"x": jnp.ones(3)}, force=True)
        assert os.path.exists(mgr._manifest_path(5))
        assert mgr.last_good_step() == 5
        assert mgr.verify_step(5)
        mgr.save(9, {"x": jnp.ones(3)}, force=True)
        assert mgr.last_good_step() == 9

    def test_corrupt_newest_falls_back_to_intact(self, hvd, tmp_path):
        mgr = self._mgr(tmp_path)
        mgr.save(1, {"x": jnp.ones(2) * 1.0}, force=True)
        mgr.save(2, {"x": jnp.ones(2) * 2.0}, force=True)
        assert corrupt_checkpoint_dir(mgr.step_path(2)) is not None
        assert not mgr.verify_step(2)
        tree, step = mgr.restore_latest({"x": jnp.zeros(2)})
        assert step == 1
        np.testing.assert_allclose(np.asarray(tree["x"]), [1.0, 1.0])
        assert mgr.corrupt_detected == 1

    def test_all_corrupt_returns_none_never_raises(self, hvd, tmp_path):
        mgr = self._mgr(tmp_path)
        mgr.save(1, {"x": jnp.ones(2)}, force=True)
        mgr.save(2, {"x": jnp.ones(2)}, force=True)
        corrupt_checkpoint_dir(mgr.step_path(1))
        corrupt_checkpoint_dir(mgr.step_path(2))
        assert mgr.restore_latest({"x": jnp.zeros(2)}) == (None, None)
        assert mgr.corrupt_detected == 2

    def test_manifestless_checkpoint_still_restores(self, hvd, tmp_path):
        """Pre-hardening checkpoints (no manifest) must stay loadable."""
        mgr = self._mgr(tmp_path)
        mgr.save(3, {"x": jnp.ones(2) * 3.0}, force=True)
        os.remove(mgr._manifest_path(3))
        assert mgr.verify_step(3)
        tree, step = mgr.restore_latest({"x": jnp.zeros(2)})
        assert step == 3

    def test_corrupt_ckpt_fault_plan_end_to_end(self, hvd, tmp_path,
                                                monkeypatch):
        """The injected corruption lands AFTER the manifest, so restore
        detects it and falls back — the acceptance scenario."""
        monkeypatch.setenv("HVDT_FAULT_PLAN", "corrupt_ckpt@step=2")
        mgr = self._mgr(tmp_path)
        mgr.save(1, {"x": jnp.ones(2) * 1.0}, force=True)
        mgr.save(2, {"x": jnp.ones(2) * 2.0}, force=True)
        inj = faults_mod.get_injector()
        assert inj.counters.get("corrupt_ckpt") == 1
        tree, step = mgr.restore_latest({"x": jnp.zeros(2)})
        assert step == 1
        np.testing.assert_allclose(np.asarray(tree["x"]), [1.0, 1.0])

    def test_prune_removes_manifests_and_last_good_follows(self, hvd,
                                                           tmp_path):
        mgr = self._mgr(tmp_path, max_to_keep=2)
        for s in (1, 2, 3, 4):
            mgr.save(s, {"x": jnp.ones(1)}, force=True)
        assert mgr.all_steps() == [3, 4]
        assert not os.path.exists(mgr._manifest_path(1))
        assert mgr.last_good_step() == 4

    def test_last_good_pointer_survives_pruned_target(self, hvd, tmp_path):
        mgr = self._mgr(tmp_path)
        mgr.save(5, {"x": jnp.ones(1)}, force=True)
        import shutil

        shutil.rmtree(mgr.step_path(5))
        mgr.save(3, {"x": jnp.ones(1)}, force=True)  # older step remains
        # Pointer says 5, 5 is gone -> newest surviving step.
        assert mgr.last_good_step() == 3


# ---------------------------------------------------------------------------
# Preemption guard
# ---------------------------------------------------------------------------

class TestPreemptionGuard:
    def test_sigterm_sets_flag_then_check_raises(self):
        saved = []
        guard = PreemptionGuard(on_preempt=lambda: saved.append(True))
        before = PreemptionGuard.emergency_checkpoints
        with guard:
            assert guard.check(step=1) is False
            os.kill(os.getpid(), signal.SIGTERM)
            for _ in range(100):
                if guard.triggered:
                    break
                time.sleep(0.01)
            assert guard.triggered
            with pytest.raises(Preempted):
                guard.check(step=2, exit=False)
        assert saved == [True]
        assert PreemptionGuard.emergency_checkpoints == before + 1

    def test_preempted_is_a_system_exit_with_the_code(self):
        exc = Preempted()
        assert isinstance(exc, SystemExit)
        assert exc.code == PREEMPT_EXIT_CODE

    def test_uninstall_restores_previous_handler(self):
        prev = signal.getsignal(signal.SIGTERM)
        guard = PreemptionGuard().install()
        assert signal.getsignal(signal.SIGTERM) != prev
        guard.uninstall()
        assert signal.getsignal(signal.SIGTERM) == prev

    def test_failing_emergency_save_still_exits_clean(self, monkeypatch):
        def broken():
            raise OSError("disk full")

        exits = []
        monkeypatch.setattr(os, "_exit", exits.append)
        guard = PreemptionGuard(on_preempt=broken)
        guard._triggered.set()
        guard.check(exit=True)
        assert exits == [PREEMPT_EXIT_CODE]

    @pytest.mark.integration
    def test_sigterm_subprocess_emergency_checkpoint_and_exit_code(
            self, tmp_path):
        """Acceptance: SIGTERM produces an emergency checkpoint and the
        clean-removal exit code (real process, real signal)."""
        out = os.path.join(tmp_path, "emergency.json")
        env = dict(os.environ, PREEMPT_TEST_OUT=out, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO + os.pathsep + os.environ.get(
                       "PYTHONPATH", ""))
        proc = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "data",
                                          "preempt_main.py")],
            env=env, cwd=REPO, stdout=subprocess.PIPE, text=True)
        assert proc.stdout.readline().strip() == "ready"
        time.sleep(0.3)
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
        assert rc == PREEMPT_EXIT_CODE
        with open(out) as f:
            payload = json.load(f)
        assert payload["emergency"] and payload["step"] > 0

    def test_driver_treats_preempt_exit_as_clean_removal(self):
        """PREEMPT_EXIT_CODE -> READY (re-rendezvous), no blacklist —
        unlike a crash exit."""
        from horovod_tpu.runner.elastic.discovery import HostManager
        from horovod_tpu.runner.elastic.driver import ElasticDriver
        from horovod_tpu.runner.hosts import HostInfo, get_host_assignments

        hm = HostManager(lambda: [HostInfo("a", 2)])
        hm.update_available_hosts()
        driver = ElasticDriver(hm, min_np=2, spawn_fn=lambda s, g: 0)
        driver._generation = 1
        driver._assignments = get_host_assignments(
            [HostInfo("a", 2)], 2)
        driver.registry.reset(2)
        driver.record_exit(driver._assignments[1], 1, PREEMPT_EXIT_CODE)
        assert driver.registry.count("READY") == 1
        assert not hm.is_blacklisted("a")
        driver.record_exit(driver._assignments[0], 1, 1)   # real crash
        assert hm.is_blacklisted("a")


# ---------------------------------------------------------------------------
# Stall escalation ladder
# ---------------------------------------------------------------------------

class TestEscalation:
    def test_rungs_fire_in_order_once(self):
        events = []
        esc = Escalator(EscalationPolicy(warn_s=1, abort_s=2, reset_s=3),
                        on_warn=lambda n, a: events.append(("warn", n)),
                        on_abort=lambda n: events.append(("abort", n)),
                        on_reset=lambda: events.append(("reset",)))
        assert esc.observe("t", 0.5) == 0
        assert esc.observe("t", 1.5) == WARN
        assert esc.observe("t", 1.6) == WARN          # no re-fire
        assert esc.observe("t", 3.5) == RESET          # abort+reset together
        assert events == [("warn", "t"), ("abort", "t"), ("reset",)]
        assert esc.counters == {"warn": 1, "abort": 1, "reset": 1}

    def test_drain_and_reset_are_one_shot(self):
        esc = Escalator(EscalationPolicy(warn_s=1, abort_s=2, reset_s=3))
        esc.observe("t", 10.0)
        assert esc.drain_aborts() == {"t"}
        assert esc.drain_aborts() == set()
        assert esc.reset_requested() is True
        assert esc.reset_requested() is False

    def test_resolve_rearms_the_ladder(self):
        esc = Escalator(EscalationPolicy(warn_s=1, abort_s=2))
        esc.observe("t", 5.0)
        esc.resolve("t")
        esc.observe("t", 5.0)
        assert esc.counters["abort"] == 2

    def test_policy_clamps_out_of_order_thresholds(self):
        p = EscalationPolicy(warn_s=60, abort_s=10, reset_s=5)
        assert p.abort_s >= p.warn_s
        assert p.reset_s >= p.abort_s

    def test_disabled_rungs_stop_the_ladder(self):
        esc = Escalator(EscalationPolicy(warn_s=1, abort_s=0, reset_s=0))
        assert esc.observe("t", 1e9) == WARN
        assert esc.drain_aborts() == set()

    def test_stall_inspector_feeds_escalator(self, monkeypatch):
        from horovod_tpu.stall import StallInspector

        monkeypatch.delenv("HVDT_STALL_CHECK_DISABLE", raising=False)
        esc = Escalator(EscalationPolicy(warn_s=0.01, abort_s=0.02))
        insp = StallInspector(world_size=2, warn_seconds=1,
                              escalator=esc)
        insp.record("grad", rank=0)      # rank 1 never shows up
        time.sleep(0.05)
        insp._last_check = 0.0
        insp.check()
        assert esc.drain_aborts() == {"grad"}
        insp.resolve("grad")             # resolution propagates
        assert esc.observe("grad", 5.0) == ABORT   # fresh episode

    def test_controller_builds_escalator_from_env(self, monkeypatch):
        """The eager controller consumes the ladder when a rung is
        configured, and aborting a stalled key emits an error response."""
        monkeypatch.setenv("HVDT_STALL_ABORT_TIME_SECONDS", "1")
        from horovod_tpu.ops.eager import EagerController
        from horovod_tpu.ops.control_plane import LocalControlPlane

        ctl = EagerController(control_plane=LocalControlPlane())
        try:
            assert ctl._escalator is not None
            assert ctl._stall.escalator is ctl._escalator
            # Simulate the coordinator seeing a stalled key, then the
            # ladder crossing the abort rung.
            from horovod_tpu.ops.messages import Request, RequestType

            req = Request(0, RequestType.ALLREDUCE, "stuck", 0, (2,))
            ctl._message_table.pending[(0, "stuck")] = {0: req}
            ctl._escalator.observe("stuck", 1e9)
            out = ctl._abort_escalated_stalls()
            assert len(out) == 1
            assert "aborted" in out[0].error_message
            assert (0, "stuck") not in ctl._message_table.pending
        finally:
            ctl.shutdown()


# ---------------------------------------------------------------------------
# Rendezvous KV hardening
# ---------------------------------------------------------------------------

class TestKVHardening:
    def _server_client(self):
        from horovod_tpu.runner.http_kv import KVClient, RendezvousServer

        server = RendezvousServer()
        port = server.start()
        client = KVClient("127.0.0.1", port, server.secret, timeout=5.0)
        return server, client

    def test_stop_kills_the_serve_thread(self):
        server, client = self._server_client()
        t = server._thread
        assert t.is_alive()
        assert server.stop() is True
        assert not t.is_alive()
        assert server._thread is None

    def test_wait_backoff_returns_value_published_midway(self):
        server, client = self._server_client()
        try:
            threading.Timer(0.2, server.put_local,
                            args=("/k", b"v")).start()
            assert client.wait("/k", timeout=10.0, poll=0.1) == b"v"
        finally:
            server.stop()

    def test_wait_timeout_raises(self):
        server, client = self._server_client()
        try:
            t0 = time.monotonic()
            with pytest.raises(TimeoutError):
                client.wait("/missing", timeout=0.5, poll=0.05)
            assert time.monotonic() - t0 < 5.0
        finally:
            server.stop()

    def test_wait_survives_injected_kv_drops(self, monkeypatch):
        """kv_drop faults make individual gets raise; the backoff loop
        absorbs them and still finds the key within the deadline."""
        monkeypatch.setenv("HVDT_FAULT_PLAN", "kv_drop@p=0.5")
        monkeypatch.setenv("HVDT_FAULT_SEED", "3")
        server, client = self._server_client()
        try:
            server.put_local("/k2", b"v2")
            assert client.wait("/k2", timeout=10.0, poll=0.05) == b"v2"
            inj = faults_mod.get_injector()
            assert inj.counters.get("kv_drop", 0) >= 1
        finally:
            server.stop()

    def test_get_raises_injected_drop_directly(self, monkeypatch):
        monkeypatch.setenv("HVDT_FAULT_PLAN", "kv_drop@p=1.0:times=1")
        server, client = self._server_client()
        try:
            with pytest.raises(ConnectionError, match="injected kv drop"):
                client.get("/x")
            assert client.get("/x") is None      # fault exhausted
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# Discovery blacklist cooldown
# ---------------------------------------------------------------------------

class TestBlacklistCooldown:
    def test_default_blacklist_is_permanent(self):
        from horovod_tpu.runner.elastic.discovery import HostState

        st = HostState()
        st.blacklist()
        assert st.is_blacklisted

    def test_cooldown_expires_and_doubles(self):
        from horovod_tpu.runner.elastic.discovery import HostState

        st = HostState(cooldown_s=0.1)
        st.blacklist()
        assert st.is_blacklisted
        time.sleep(0.15)
        assert not st.is_blacklisted       # transient crash forgiven
        st.blacklist()                     # second failure: 2x cooldown
        time.sleep(0.15)
        assert st.is_blacklisted
        time.sleep(0.1)
        assert not st.is_blacklisted
        assert st.failures == 2

    def test_env_knob_drives_default(self, monkeypatch):
        from horovod_tpu.runner.elastic.discovery import HostState

        monkeypatch.setenv("HVDT_ELASTIC_BLACKLIST_COOLDOWN_S", "0.05")
        st = HostState()
        st.blacklist()
        time.sleep(0.1)
        assert not st.is_blacklisted


# ---------------------------------------------------------------------------
# Serve reload hardening
# ---------------------------------------------------------------------------

class TestServeReloadHardening:
    def test_failure_streak_and_last_good_gauge(self, hvd, tmp_path):
        from horovod_tpu.checkpoint import CheckpointManager
        from horovod_tpu.serve.reload import CheckpointWatcher

        mgr = CheckpointManager(os.path.join(tmp_path, "c"), max_to_keep=10)
        mgr.save(1, {"x": jnp.ones(2) * 1.0}, force=True)
        seen = []
        watcher = CheckpointWatcher(
            mgr, template={"x": jnp.zeros(2)},
            on_reload=lambda tree, step: seen.append(step),
            poll_interval_s=0.05)
        assert watcher.check_once() == 1
        assert watcher._fail_streak == 0
        # Corrupt the next step: its manifest verification fails, so the
        # watcher SKIPS it (counted, but no failure streak — a corrupt
        # newest step must not slow the poll down) and keeps serving
        # step 1.
        mgr.save(2, {"x": jnp.ones(2) * 2.0}, force=True)
        corrupt_checkpoint_dir(mgr.step_path(2))
        assert watcher.check_once() is None
        assert watcher._fail_streak == 0
        assert watcher.current_step == 1
        # A good step arrives: reload succeeds immediately.
        mgr.save(3, {"x": jnp.ones(2) * 3.0}, force=True)
        assert watcher.check_once() == 3
        assert watcher._fail_streak == 0
        assert seen == [1, 3]
        text = watcher.metrics.render()
        assert "serve_last_good_step 3" in text
        assert "serve_skipped_unverified_total 1" in text
        assert "serve_reload_failures_total 0" in text

    def test_reload_fault_point(self, hvd, tmp_path, monkeypatch):
        from horovod_tpu.checkpoint import CheckpointManager
        from horovod_tpu.serve.reload import CheckpointWatcher

        monkeypatch.setenv("HVDT_FAULT_PLAN",
                           "exc@point=serve.reload:step=1")
        mgr = CheckpointManager(os.path.join(tmp_path, "c"), max_to_keep=10)
        mgr.save(1, {"x": jnp.ones(2)}, force=True)
        watcher = CheckpointWatcher(
            mgr, template={"x": jnp.zeros(2)},
            on_reload=lambda tree, step: None, poll_interval_s=0.05)
        # Injected failure is absorbed by the watcher's failure policy.
        assert watcher.check_once() is None
        assert watcher._fail_streak == 1


# ---------------------------------------------------------------------------
# TCP connect retry (stubbed native group)
# ---------------------------------------------------------------------------

class TestTcpConnectRetry:
    def test_bootstrap_retries_then_succeeds(self, monkeypatch):
        from horovod_tpu.ops import tcp_backend
        from horovod_tpu import native as native_mod

        attempts = []

        class FakeGroup:
            def __init__(self, rank, size, addrs, timeout_ms=0):
                attempts.append(1)
                if len(attempts) < 3:
                    raise native_mod.NativeError(1, "connect refused")

            def close(self):
                pass

        class PS:
            id = 7
            ranks = [0]

            def rank(self):
                return 0

            def size(self):
                return 1

        monkeypatch.setenv("HVDT_TCP_ADDRS", "127.0.0.1:49000")
        monkeypatch.setattr(native_mod, "TcpProcessGroup", FakeGroup)
        monkeypatch.setattr(tcp_backend, "_groups", {})
        g = tcp_backend.group_for(PS())
        assert isinstance(g, FakeGroup)
        assert len(attempts) == 3

    def test_bootstrap_exhaustion_raises(self, monkeypatch):
        from horovod_tpu.ops import tcp_backend
        from horovod_tpu import native as native_mod

        class DeadGroup:
            def __init__(self, *a, **kw):
                raise native_mod.NativeError(1, "nope")

        class PS:
            id = 8
            ranks = [0]

            def rank(self):
                return 0

            def size(self):
                return 1

        monkeypatch.setenv("HVDT_TCP_ADDRS", "127.0.0.1:49100")
        monkeypatch.setattr(native_mod, "TcpProcessGroup", DeadGroup)
        monkeypatch.setattr(tcp_backend, "_groups", {})
        with pytest.raises(RetriesExhausted):
            tcp_backend.group_for(PS())


# ---------------------------------------------------------------------------
# CLI wiring
# ---------------------------------------------------------------------------

class TestCliWiring:
    def test_fault_plan_flag_forwards_as_env(self):
        from horovod_tpu.runner.launch import knob_env_for, parse_args

        args = parse_args(["--fault-plan", "crash@step=5:rank=1",
                           "--blacklist-cooldown", "2.5",
                           "--stall-abort-time-seconds", "30",
                           "-np", "2", "--", "python", "train.py"])
        env = knob_env_for(args)
        assert env["HVDT_FAULT_PLAN"] == "crash@step=5:rank=1"
        assert env["HVDT_ELASTIC_BLACKLIST_COOLDOWN_S"] == "2.5"
        assert env["HVDT_STALL_ABORT_TIME_SECONDS"] == "30"

    def test_fault_journal_survives_process_restart(self, tmp_path,
                                                    monkeypatch):
        """Once-only faults must stay once-only across elastic respawns:
        a fresh injector with the same journal sees the fired count."""
        journal = os.path.join(tmp_path, "j")
        monkeypatch.setenv("HVDT_FAULT_PLAN", "exc@step=5")
        monkeypatch.setenv("HVDT_FAULT_JOURNAL", journal)
        monkeypatch.setenv("HVDT_RANK", "0")
        inj1 = FaultInjector.from_env()
        with pytest.raises(InjectedFault):
            inj1.fire("step", step=10, rank=0)
        inj2 = FaultInjector.from_env()   # the "respawned" process
        inj2.fire("step", step=15, rank=0)   # must NOT re-fire
        assert inj2.fired_total() == 0
        assert inj2.specs[0].fired == 1   # loaded from the journal


# ---------------------------------------------------------------------------
# Multiprocess chaos: kill one worker mid-training, elastic recovery
# ---------------------------------------------------------------------------

def _rows(path):
    out = []
    with open(path) as f:
        for ln in f:
            if ln.strip():
                r, s, b, lr, ts = map(int, ln.split())
                out.append((r, s, b, lr, ts))
    return out


@pytest.mark.integration
def test_injected_crash_recovers_with_step_continuity(tmp_path):
    """Acceptance scenario: HVDT_FAULT_PLAN kills rank 1 at a commit
    point mid-training.  The hardened stack must recover — the
    survivor's peer-stall detection converts the dead peer into the
    elastic restore path (HorovodInternalError → exit-for-respawn), the
    cooldown blacklist lets the host rejoin, and the new generation
    resumes from the disk commit with monotone step continuation and
    loss continuity to the target batch count.

    (The coupling rides rendezvous-KV heartbeats, not eager collectives:
    the container's CPU jax cannot run multiprocess XLA computations —
    the pre-existing test_elastic_integration failures — and the
    recovery machinery under test is identical either way; see
    tests/data/resilient_main.py.)"""
    log_path = os.path.join(tmp_path, "progress.log")
    env = dict(os.environ)
    env.update({
        "ELASTIC_TEST_LOG": log_path,
        "ELASTIC_TEST_STATE": os.path.join(tmp_path, "state.pkl"),
        "ELASTIC_TEST_BATCHES": "30",
        "ELASTIC_TEST_SLEEP": "0.1",
        "ELASTIC_TEST_HB_TIMEOUT": "6",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
        # The chaos knobs under test:
        "HVDT_FAULT_PLAN": "crash@step=10:rank=1",
        "HVDT_FAULT_JOURNAL": os.path.join(tmp_path, "fault_journal"),
        "HVDT_ELASTIC_BLACKLIST_COOLDOWN_S": "1",
    })
    discover = os.path.join(tmp_path, "discover.sh")
    with open(discover, "w") as f:
        f.write("#!/bin/sh\necho localhost:2\n")
    os.chmod(discover, 0o755)
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "--min-np", "2", "--max-np", "2",
         "--host-discovery-script", discover,
         "--coordinator-port", "29761",
         "--", sys.executable, os.path.join(REPO, "tests", "data",
                                            "resilient_main.py")],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    try:
        out, _ = proc.communicate(timeout=300)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        pytest.fail(f"chaos run hung:\n{out.decode()[-3000:]}")
    assert proc.returncode == 0, out.decode()[-3000:]

    rows = _rows(log_path)
    # Training reached the target despite the mid-training kill.
    assert max(b for _, _, b, _, _ in rows) == 30
    # Rank 1 died at its batch-10 commit and came back: it logged batches
    # past the crash point...
    r1_batches = [b for r, _, b, _, _ in rows if r == 1]
    assert max(r1_batches) == 30
    # ...and the recovered generation resumed from the disk commit, not
    # from scratch (monotone continuation: no restart at batch 1).
    post_crash = [b for b in r1_batches if b > 10]
    assert post_crash, "rank 1 never progressed past the injected crash"
    assert min(post_crash) == 11
    resumed_from = r1_batches[r1_batches.index(11) - 1] \
        if r1_batches.index(11) > 0 else 0
    assert resumed_from >= 5, (
        f"recovered worker resumed from batch {resumed_from}, "
        f"not from the last commit")
    # Both ranks finished the final world.
    assert {r for r, _, b, _, _ in rows if b == 30} == {0, 1}
    # Loss continuity: every batch applied its update exactly once
    # across crash/restore/replay (w0 == 30 batches * lr 0.2).
    assert "final: batches=30 w0=6.0" in out.decode()
    # Recovery-time budget: "we recovered" is not enough — the wall
    # clock from rank 1's death (its last pre-crash batch-10 line) to
    # its first NEW batch (11) must stay under the 30 s SLO.
    r1_rows = sorted((ts, b) for r, _, b, _, ts in rows if r == 1)
    t_kill = min(ts for ts, b in r1_rows if b == 10)
    t_recovered = min(ts for ts, b in r1_rows if b == 11)
    recovery_s = (t_recovered - t_kill) / 1000.0
    assert recovery_s < 30.0, (
        f"rank 1 recovery took {recovery_s:.1f}s (budget 30s)")
