"""Native C++ core tests: TCP collective backend, Adasum VHDD, timeline.

Multi-rank coverage runs N ranks as N threads in this process — the
ctypes calls block in C++ with the GIL released, so a full socket mesh on
localhost exercises the real wire path (analog of the reference's
2-process mpirun tier, SURVEY.md §4, without spawning processes).
"""

import json
import os
import socket
import threading

import numpy as np
import pytest

from horovod_tpu import native


pytestmark = pytest.mark.skipif(
    not native.available(), reason="native core not built/available")


def _free_ports(n):
    socks = []
    ports = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def run_ranks(size, fn):
    """Run fn(group, rank) on `size` connected ranks, return rank-ordered
    results; re-raises the first worker exception."""
    ports = _free_ports(size)
    addrs = [f"127.0.0.1:{p}" for p in ports]
    results = [None] * size
    errors = []

    def worker(rank):
        try:
            with native.TcpProcessGroup(rank, size, addrs,
                                        timeout_ms=15000) as g:
                results[rank] = fn(g, rank)
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errors.append((rank, e))

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    if errors:
        raise errors[0][1]
    assert all(not t.is_alive() for t in threads), "worker hung"
    return results


@pytest.mark.parametrize("size", [2, 3, 4])
@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32,
                                   np.int64, np.uint8])
def test_allreduce_sum(size, dtype):
    n = 1000

    def fn(g, rank):
        x = (np.arange(n) % 17 + rank).astype(dtype)
        return g.allreduce(x)

    results = run_ranks(size, fn)
    base = np.arange(n) % 17
    expected = (base * size + sum(range(size))).astype(dtype)
    for r in results:
        np.testing.assert_array_equal(r, expected)


@pytest.mark.parametrize("op,npop", [("MIN", np.minimum), ("MAX", np.maximum)])
def test_allreduce_minmax(op, npop):
    from horovod_tpu.common.types import ReduceOp

    size = 3
    rng = np.random.default_rng(0)
    inputs = [rng.normal(size=37).astype(np.float32) for _ in range(size)]

    def fn(g, rank):
        return g.allreduce(inputs[rank], op=ReduceOp[op])

    results = run_ranks(size, fn)
    expected = inputs[0]
    for x in inputs[1:]:
        expected = npop(expected, x)
    for r in results:
        np.testing.assert_allclose(r, expected, rtol=1e-6)


def test_allreduce_average():
    from horovod_tpu.common.types import ReduceOp

    size = 4

    def fn(g, rank):
        return g.allreduce(np.full(5, rank + 1, np.float32),
                           op=ReduceOp.AVERAGE)

    for r in run_ranks(size, fn):
        np.testing.assert_allclose(r, np.full(5, 2.5, np.float32))


def test_allreduce_bfloat16():
    import ml_dtypes

    size = 2
    bf16 = np.dtype(ml_dtypes.bfloat16)

    def fn(g, rank):
        return g.allreduce(np.full(64, 1.5 + rank, bf16))

    for r in run_ranks(size, fn):
        np.testing.assert_allclose(np.asarray(r, np.float32),
                                   np.full(64, 4.0, np.float32))


def test_allreduce_small_count_more_ranks():
    # count < size exercises zero-length ring segments
    size = 4

    def fn(g, rank):
        return g.allreduce(np.array([float(rank)], np.float32))

    for r in run_ranks(size, fn):
        np.testing.assert_allclose(r, [6.0])


@pytest.mark.parametrize("size", [2, 3])
def test_allgather_variable_rows(size):
    def fn(g, rank):
        t = np.full((rank + 1, 3), rank, np.float32)
        return g.allgather(t)

    expected = np.concatenate(
        [np.full((r + 1, 3), r, np.float32) for r in range(size)])
    for r in run_ranks(size, fn):
        np.testing.assert_array_equal(r, expected)


def test_broadcast():
    size = 3
    payload = np.arange(11, dtype=np.int64) * 7

    def fn(g, rank):
        x = payload.copy() if rank == 1 else np.zeros(11, np.int64)
        return g.broadcast(x, root=1)

    for r in run_ranks(size, fn):
        np.testing.assert_array_equal(r, payload)


@pytest.mark.parametrize("size", [2, 3])
def test_alltoall_uneven_splits(size):
    # rank r sends (d+1) rows to destination d, each row stamped (src, dst)
    def fn(g, rank):
        rows = []
        splits = []
        for dst in range(size):
            k = dst + 1
            splits.append(k)
            rows.append(np.full((k, 2), [rank, dst], np.int32))
        return g.alltoall(np.concatenate(rows), splits=splits)

    results = run_ranks(size, fn)
    for rank, out in enumerate(results):
        expected = np.concatenate(
            [np.full((rank + 1, 2), [src, rank], np.int32)
             for src in range(size)])
        np.testing.assert_array_equal(out, expected)


def test_barrier_and_rank_size():
    size = 3

    def fn(g, rank):
        assert g.rank == rank and g.size == size
        g.barrier()
        return True

    assert run_ranks(size, fn) == [True] * size


# ---- Adasum ----


def test_adasum_combine_math():
    # orthogonal vectors -> plain sum; identical vectors -> average... of
    # the *pair*: a' = (1 - 1/2)a + (1 - 1/2)a = a  (scale invariance).
    a = np.array([1.0, 0.0], np.float32)
    b = np.array([0.0, 1.0], np.float32)
    np.testing.assert_allclose(native.adasum_combine(a, b), [1.0, 1.0])
    c = np.array([2.0, 3.0], np.float32)
    np.testing.assert_allclose(native.adasum_combine(c, c), c, rtol=1e-6)


@pytest.mark.parametrize("size", [2, 4])
def test_adasum_allreduce_matches_pairwise_tree(size):
    rng = np.random.default_rng(1)
    inputs = [rng.normal(size=64).astype(np.float32) for _ in range(size)]

    def fn(g, rank):
        return g.adasum_allreduce(inputs[rank])

    results = run_ranks(size, fn)
    # All ranks agree.
    for r in results[1:]:
        np.testing.assert_allclose(r, results[0], rtol=1e-5, atol=1e-6)
    # VHDD equals the recursive pairwise combine tree on full vectors.
    level = [x.astype(np.float64) for x in inputs]
    while len(level) > 1:
        level = [
            native.adasum_combine(level[i], level[i + 1])
            for i in range(0, len(level), 2)
        ]
    np.testing.assert_allclose(results[0], level[0].astype(np.float32),
                               rtol=1e-4, atol=1e-5)


def test_adasum_requires_power_of_two():
    def fn(g, rank):
        g.adasum_allreduce(np.ones(4, np.float32))

    with pytest.raises(native.NativeError, match="power-of-two"):
        run_ranks(3, fn)


# ---- timeline ----


def test_native_timeline_writes_chrome_trace(tmp_path):
    path = os.path.join(tmp_path, "tl.json")
    with native.NativeTimeline(path) as tl:
        tl.begin("grad/layer0", "NEGOTIATE_ALLREDUCE")
        tl.end("grad/layer0", "NEGOTIATE_ALLREDUCE")
        tl.complete("grad/layer0", "ALLREDUCE", 100, 250,
                    args={"bytes": 4096})
        tl.instant("grad/layer1", "CYCLE_START")
    raw = open(path).read().rstrip().rstrip(",")
    events = json.loads(raw + "]")
    names = [e["name"] for e in events]
    assert "process_name" in names  # pid metadata rows
    assert "NEGOTIATE_ALLREDUCE" in names and "ALLREDUCE" in names
    x = [e for e in events if e["ph"] == "X"][0]
    assert x["dur"] == 250 and x["args"]["bytes"] == 4096
    # two distinct tensors -> two pid rows
    pids = {e["pid"] for e in events if e["ph"] != "M"}
    assert len(pids) == 2


# ---- HVDT_CPU_OPERATIONS=tcp backend wiring ----


class _FakeProcessSet:
    """Stands in for common.process_sets.ProcessSet in backend tests."""

    def __init__(self, set_id, my_rank, ranks):
        self.id = set_id
        self.ranks = list(ranks)
        self._my = my_rank

    def rank(self):
        return self.ranks.index(self._my)

    def size(self):
        return len(self.ranks)


def test_tcp_backend_dispatch(monkeypatch):
    from horovod_tpu.ops import tcp_backend
    from horovod_tpu.ops import host_collectives as hostc
    from horovod_tpu.common.types import ReduceOp

    size = 2
    ports = _free_ports(size)
    monkeypatch.setenv("HVDT_CPU_OPERATIONS", "tcp")
    monkeypatch.setenv(
        "HVDT_TCP_ADDRS", ",".join(f"127.0.0.1:{p}" for p in ports))
    assert tcp_backend.enabled()

    results = [None] * size
    errors = []

    def worker(rank):
        try:
            ps = _FakeProcessSet(0, rank, range(size))
            r1 = hostc.host_allreduce(
                np.full(9, rank + 1.0, np.float32), ps, ReduceOp.SUM)
            r2 = hostc.host_broadcast(
                np.arange(4.0, dtype=np.float32) if rank == 0 else None,
                0, ps, (4,), np.float32)
            r3 = hostc.host_allgather(
                np.full((rank + 1, 2), rank, np.int32), ps,
                [1, 2])
            results[rank] = (r1, r2, r3)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    # Reset the cached groups before asserting (other tests run clean).
    tcp_backend.shutdown_groups()
    if errors:
        raise errors[0]
    for r1, r2, r3 in results:
        np.testing.assert_allclose(r1, np.full(9, 3.0, np.float32))
        np.testing.assert_allclose(r2, np.arange(4.0, dtype=np.float32))
        expected = np.concatenate([np.full((1, 2), 0, np.int32),
                                   np.full((2, 2), 1, np.int32)])
        np.testing.assert_array_equal(r3, expected)
