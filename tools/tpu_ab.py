"""Quiet-window TPU A/B runner (VERDICT r3 #1-#4 evidence collector).

Runs a fixed sequence of experiment legs as subprocesses on the real
chip, parses each leg's metric line, and appends everything to
``tools/ab_results.json``.  Designed to run unattended the moment the
tunnelled chip comes back: leg 0 is the stock ResNet bench (which also
refreshes bench.py's last-good cache), then the LM legs, then the
flash-backward kernel A/Bs.

Sequential by construction — this box has one core and one chip, and
only within-one-window comparisons are valid (docs/performance.md).
"""

import argparse
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable

LM = [PY, os.path.join(REPO, "examples", "jax_transformer_lm.py"),
      "--preset", "bert-large", "--dp", "1", "--tp", "1",
      "--dtype", "bfloat16"]
TOKS = re.compile(r"(\d+) tokens/sec, ~([\d.]+) model TFLOP/s")


def lm_leg(name, extra, steps="30", timeout=900, env=None):
    return {"name": name,
            "cmd": LM + ["--steps", steps] + extra,
            "timeout": timeout, "env": env,
            "parse": lambda out: (
                {"tokens_per_sec": int(TOKS.search(out).group(1)),
                 "model_tflops": float(TOKS.search(out).group(2))}
                if TOKS.search(out) else None)}


def json_leg(name, cmd, timeout=900, env=None):
    def parse(out):
        for line in reversed(out.strip().splitlines()):
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except ValueError:
                    continue
        return None
    return {"name": name, "cmd": cmd, "timeout": timeout, "parse": parse,
            "env": env}


def jsonl_leg(name, cmd, timeout=900, expect=None):
    """All JSON lines, in order (multi-shape probes emit one per shape).

    ``expect``: required row count — a probe that crashes mid-run after
    emitting a prefix of its shapes must record as FAILED, not as a
    complete measurement (``require_rc0`` backs this with the exit
    code)."""
    def parse(out):
        rows = []
        for line in out.strip().splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    continue
        if not rows or (expect is not None and len(rows) != expect):
            return None
        return rows
    return {"name": name, "cmd": cmd, "timeout": timeout, "parse": parse,
            "require_rc0": True}


def raw_leg(name, cmd, timeout=900, keep=8000, marker="by category:",
            env=None):
    """Keep stdout from the report marker on (profile tables etc.).
    Success requires the marker — partial stdout before a crash must not
    record as ok."""
    def parse(out):
        i = out.find(marker)
        if i < 0:
            return None
        return {"raw": out[i:i + keep]}
    return {"name": name, "cmd": cmd, "timeout": timeout, "parse": parse,
            "env": env}


LEGS = [
    # Refresh the headline bench FIRST (also writes .bench_last_good.json).
    json_leg("resnet_bench_default",
             [PY, os.path.join(REPO, "bench.py")], timeout=1500),
    # IMMEDIATELY after the default: the FULL bench with every eligible
    # bottleneck 1x1 routed through the fused Pallas kernels
    # (models/resnet.py _conv_bn) — adjacent legs give the tightest
    # within-window e2e A/B; >=2% img/s flips HVDT_FUSED_CONV1X1.
    json_leg("resnet_bench_fused",
             [PY, os.path.join(REPO, "bench.py")], timeout=1500,
             env={"HVDT_FUSED_CONV1X1": "1",
                  # A/B probe, not the headline: do not overwrite the
                  # last-good cache with the experimental config.
                  "HVDT_BENCH_NO_CACHE": "1",
                  "HVDT_BENCH_PROFILE": "0"}),
    # LM: reproduce the round-2/3 baseline.  (The no-remat legs are
    # ANSWERED — r4 measured OOM at batch>=32, tools/ab_results.json —
    # and removed; remat "full" is the only feasible bs128 config.)
    lm_leg("lm_base_bs128_remat", ["--batch", "128"]),
    # Smallseq legs IMMEDIATELY after their baseline: tightest window
    # for the round's highest-value A/B (the ~0.4-0.7 s/step estimate
    # standing between 41% and >=50% MFU), and first in line if the
    # chip answers late in a round.  Baseline to beat: 29,374 tok/s.
    lm_leg("lm_smallseq_hb8_bs128", ["--batch", "128"],
           env={"HVDT_FLASH_SMALLSEQ": "on"}),
    lm_leg("lm_smallseq_hb16_bs128", ["--batch", "128"],
           env={"HVDT_FLASH_SMALLSEQ": "on",
                "HVDT_FLASH_SMALLSEQ_HB": "16"}),
    lm_leg("lm_smallseq_hb4_bs128", ["--batch", "128"],
           env={"HVDT_FLASH_SMALLSEQ": "on",
                "HVDT_FLASH_SMALLSEQ_HB": "4"}),
    # Where does the smallseq step go?  (Shows immediately whether the
    # wrapper's [B,L,H,D]<->[B,H,L,D] transposes matter.)
    raw_leg("lm_smallseq_profile_bs128",
            LM + ["--batch", "128", "--steps", "10", "--profile"],
            timeout=1200, env={"HVDT_FLASH_SMALLSEQ": "on"}),
    # Where do the non-matmul 45% of the bs128 step go?  3-step XPlane
    # per-category breakdown (examples/jax_transformer_lm.py --profile).
    raw_leg("lm_profile_bs128",
            LM + ["--batch", "128", "--steps", "10", "--profile"],
            timeout=1200),
    # bs64 with the (now-default) chunked xent at a long timed region —
    # the round-2 49.5 TFLOP bs64 row predates both.
    lm_leg("lm_bs64_long", ["--batch", "64", "--steps", "120"],
           timeout=1200),
    # Full-Pallas attention at the flagship shape: round-2 measured XLA
    # attention ~1.5x faster than kernel-fwd + BLOCKWISE-XLA bwd at
    # seq 512 — but the round-3 flash_grad_block kernel bwd was never in
    # that comparison.  If kernel+kernel beats XLA end-to-end here, the
    # auto gate's 4 GB threshold is wrong and the defaults flip.
    lm_leg("lm_flash_kernelbwd_bs128", ["--batch", "128"],
           env={"HVDT_FLASH_ATTENTION": "on", "HVDT_FLASH_BWD": "kernel"}),
    lm_leg("lm_flash_xlabwd_bs128", ["--batch", "128"],
           env={"HVDT_FLASH_ATTENTION": "on"}),
    # Flash backward kernel vs XLA blockwise (the knob-flip evidence).
    json_leg("bwd_ab_seq2048",
             [PY, os.path.join(REPO, "tools", "bwd_ab.py"),
              "--seq", "2048", "--batch", "16"], timeout=1500),
    json_leg("bwd_ab_seq4096",
             [PY, os.path.join(REPO, "tools", "bwd_ab.py"),
              "--seq", "4096", "--batch", "8"], timeout=1500),
    json_leg("bwd_ab_seq8192",
             [PY, os.path.join(REPO, "tools", "bwd_ab.py"),
              "--seq", "8192", "--batch", "4"], timeout=1500),
    # Chunked-xent scan granularity: 2 chunks of 16384 vs 4 of 8192 —
    # fewer sequential scan steps vs a 4.3 GB live logits tile.
    lm_leg("lm_chunk16384_bs128", ["--batch", "128",
                                   "--loss-chunk", "16384"]),
    # e2e confirmation of the bwd_ab seq-4096 kernel win (1.14x
    # backward-only): long-context config, flash fwd auto-engaged
    # (score bytes >= 4 GB), backward knob A/B.
    lm_leg("lm_seq4096_fbwd_kernel", ["--batch", "16", "--seq", "4096"],
           env={"HVDT_FLASH_BWD": "kernel"}, timeout=1200),
    lm_leg("lm_seq4096_fbwd_xla", ["--batch", "16", "--seq", "4096"],
           timeout=1200),
    # Ring attention per-step block primitives, Pallas vs jnp (the
    # HVDT_RING_PALLAS evidence — sp>=2 can't run on one chip, but the
    # ring cost is sp repetitions of exactly these two per-device ops).
    json_leg("ring_ab_local2048",
             [PY, os.path.join(REPO, "tools", "ring_ab.py"),
              "--local-seqs", "2048", "--batch", "2"], timeout=1200),
    json_leg("ring_ab_local8192",
             [PY, os.path.join(REPO, "tools", "ring_ab.py"),
              "--local-seqs", "8192", "--batch", "1"], timeout=1200),
    # Below-XLA ResNet roofline probe (VERDICT r4 weak #3): fused
    # 1x1-conv+BN Pallas epilogue vs XLA conv/matmul scheduling on the
    # four hot bottleneck shapes — one JSON row per shape.
    jsonl_leg("resnet_1x1_probe",
              [PY, os.path.join(REPO, "tools", "resnet_probe.py")],
              timeout=1500, expect=4),
    # TRAIN-form BN (batch stats): the fused kernel emits z + stat
    # partials in one pass, saving one full read of z vs XLA's
    # stats-then-normalize schedule.
    jsonl_leg("resnet_1x1_train_probe",
              [PY, os.path.join(REPO, "tools", "resnet_probe.py"),
               "--form", "train"],
              timeout=1500, expect=4),
    # ResNet dispatch-gap probe: N steps per jit call via lax.fori_loop
    # (larger batches were already measured WORSE in round 2 — activation
    # traffic scales with batch; docs/performance.md).
    json_leg("resnet_steps_per_call10",
             [PY, os.path.join(REPO, "bench.py"), "--steps-per-call", "10",
              "--num-batches-per-iter", "5"], timeout=1500),
]

# Failure tails that mean THE LEG is infeasible (OOM etc.), not that the
# chip is down — these must not trip the consecutive-failure abort (r4:
# two no-remat OOM legs aborted the harness while the chip was healthy).
_LEG_SPECIFIC = ("RESOURCE_EXHAUSTED", "AllocateBuffer", "Allocation type",
                 "out of memory", "OOM")


def run_leg(leg, env):
    t0 = time.time()
    if leg.get("env"):
        env = dict(env, **leg["env"])
    try:
        proc = subprocess.run(leg["cmd"], env=env, capture_output=True,
                              text=True, timeout=leg["timeout"], cwd=REPO)
        out = proc.stdout + "\n" + proc.stderr
        parsed = leg["parse"](proc.stdout)
        if parsed is not None and leg.get("require_rc0") \
                and proc.returncode != 0:
            # Parsable prefix + crash = incomplete evidence, not a run.
            parsed = None
        return {"name": leg["name"], "ok": parsed is not None,
                "wall_s": round(time.time() - t0, 1),
                "result": parsed,
                "tail": None if parsed else out[-800:]}
    except subprocess.TimeoutExpired:
        return {"name": leg["name"], "ok": False,
                "wall_s": round(time.time() - t0, 1),
                "result": None, "tail": f"timeout {leg['timeout']}s"}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", help="comma-separated leg names")
    ap.add_argument("--out", default=os.path.join(REPO, "tools",
                                                  "ab_results.json"))
    args = ap.parse_args()
    legs = LEGS
    if args.only:
        want = set(args.only.split(","))
        legs = [l for l in LEGS if l["name"] in want]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("HVDT_BENCH_ATTEMPT_TIMEOUTS", "600")
    results = []
    fails = 0
    for leg in legs:
        print(f"=== {leg['name']} ===", flush=True)
        r = run_leg(leg, env)
        print(json.dumps(r), flush=True)
        results.append(r)
        leg_specific = r["tail"] and any(m in r["tail"]
                                         for m in _LEG_SPECIFIC)
        # OOM legs neither accumulate toward chip-down nor clear evidence
        # of it — only a SUCCESS proves the chip is alive.
        fails = 0 if r["ok"] else (fails if leg_specific else fails + 1)
        if fails >= 2:
            print("two consecutive failures — chip likely down, aborting",
                  flush=True)
            break
    hist = []
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                hist = json.load(f)
        except ValueError:
            hist = []
    hist.append({"at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                 "results": results})
    with open(args.out, "w") as f:
        json.dump(hist, f, indent=1)
    print(f"saved {len(results)} legs -> {args.out}")


if __name__ == "__main__":
    main()
