"""A/B: Pallas flash backward kernels vs the blockwise-XLA backward.

Measures the backward-only cost of both paths at a given shape and
prints one JSON line — the evidence VERDICT r3 #3 asks for before the
HVDT_FLASH_BWD default can be flipped.  Timing follows the repo
contract: each timed region ends with a host fetch of a scalar that
data-depends on the result (block_until_ready is a no-op over the
tunnel — docs/performance.md).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.ops.pallas_kernels import (_flash_fwd_core,
                                            flash_attention,
                                            flash_grad_block)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=4096)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--iters", type=int, default=30)
    args = ap.parse_args()

    b, L, h, d = args.batch, args.seq, args.heads, args.dim
    q = jax.random.normal(jax.random.PRNGKey(0), (b, L, h, d), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, L, h, d), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, L, h, d), jnp.bfloat16)
    do = jax.random.normal(jax.random.PRNGKey(3), (b, L, h, d),
                           jnp.bfloat16)

    @jax.jit
    def xla_bwd(q, k, v, do):
        _, vjp = jax.vjp(
            lambda q, k, v: flash_attention(q, k, v, causal=True), q, k, v)
        return vjp(do)

    @jax.jit
    def pallas_bwd(q, k, v, do):
        out, lse = _flash_fwd_core(q, k, v, True, d ** -0.5, 512, 512)
        return flash_grad_block(q, k, v, do, out, lse, causal=True,
                                scale=d ** -0.5)

    def fetch(r):
        return float(jnp.asarray(r[0]).ravel()[0].astype(jnp.float32))

    def bench(f):
        r = f(q, k, v, do)
        fetch(r)                              # compile + sync
        t0 = time.perf_counter()
        for _ in range(args.iters):
            r = f(q, k, v, do)
        fetch(r)                              # host fetch ends the region
        return (time.perf_counter() - t0) / args.iters

    # correctness gate before timing: a numerically wrong kernel must
    # not publish a speedup that could flip the HVDT_FLASH_BWD default.
    # The diff reduces ON DEVICE — fetching the full gradient tensors to
    # the host (GBs at these shapes) takes longer than the tunnelled
    # chip's 900 s A/B budget.  It takes the ALREADY-COMPUTED gradients,
    # so neither backward is compiled or executed a second time.
    @jax.jit
    def rel_diff(r1, r2):
        rels = [jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)).max()
                / jnp.maximum(jnp.abs(a.astype(jnp.float32)).max(), 1e-9)
                for a, b in zip(r1, r2)]
        return jnp.stack(rels).max()

    def stage(msg):
        print(f"[{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
              flush=True)

    stage("compiling+running xla_bwd")
    rx = xla_bwd(q, k, v, do)
    stage("xla_bwd dispatched; fetching")
    fetch0 = float(jnp.asarray(rx[0]).ravel()[0].astype(jnp.float32))
    stage(f"xla_bwd done ({fetch0:.3g}); compiling+running pallas_bwd")
    rp = pallas_bwd(q, k, v, do)
    fetch1 = float(jnp.asarray(rp[0]).ravel()[0].astype(jnp.float32))
    stage(f"pallas_bwd done ({fetch1:.3g}); computing on-device diff")
    rel = float(rel_diff(list(rx), list(rp)))
    stage(f"rel diff {rel:.3g}")
    correct = rel < 5e-2       # bf16 inputs, f32 accumulation
    t_x = bench(xla_bwd)
    t_p = bench(pallas_bwd) if correct else None
    dev = jax.devices()[0]
    print(json.dumps({
        "metric": "flash_bwd_ab", "platform": dev.platform,
        "device_kind": dev.device_kind,
        "shape": {"batch": b, "seq": L, "heads": h, "dim": d},
        "rel_max_diff": rel,
        "correctness_ok": correct,
        "xla_ms": round(t_x * 1000, 2),
        "pallas_ms": round(t_p * 1000, 2) if correct else None,
        "pallas_speedup": round(t_x / t_p, 3) if correct else None,
    }))
    if not correct:
        sys.exit(1)


if __name__ == "__main__":
    main()
