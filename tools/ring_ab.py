"""A/B: ring attention's per-step block primitives, Pallas vs jnp.

VERDICT r3 #3 evidence for the ``HVDT_RING_PALLAS`` default.  sp>=2
cannot run on the one real chip, but the ring's cost is sp repetitions
of exactly two per-device primitives (parallel/ring_attention.py):

  fwd step:  _block_update (jnp)        vs flash_block_update (Pallas)
  bwd step:  the blockwise jnp VJP body vs flash_grad_block (Pallas)

Both are pure per-device ops — measuring them on one chip at the
ring-local shard shapes IS the per-step cost a ring member pays; the
ppermute transfer rides ICI concurrently (np=8 CPU path covers the
schedule).  Prints one JSON line per shape.  Timing follows the repo
contract: each timed region ends with a host fetch of a scalar that
data-depends on the result (block_until_ready is a no-op over the
tunnel — docs/performance.md).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from horovod_tpu.ops.pallas_kernels import (flash_block_update,
                                            flash_grad_block)
from horovod_tpu.parallel.ring_attention import (_NEG_INF, _block_update,
                                                 _bwd_block_grads)


def bench(f, args_, iters, fetch):
    r = f(*args_)
    fetch(r)                               # compile + sync
    t0 = time.perf_counter()
    for _ in range(iters):
        r = f(*args_)
    fetch(r)                               # host fetch ends the region
    return (time.perf_counter() - t0) / iters


def run_shape(b, l, h, d, iters):
    """l is the LOCAL (per-ring-member) sequence shard."""
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    q = jax.random.normal(ks[0], (b, l, h, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, l, h, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, l, h, d), jnp.bfloat16)
    do = jax.random.normal(ks[3], (b, l, h, d), jnp.bfloat16)
    acc = jnp.zeros((b, l, h, d), jnp.float32)
    m0 = jnp.full((b, h, l), _NEG_INF, jnp.float32)
    s0 = jnp.zeros((b, h, l), jnp.float32)
    scale = d ** -0.5
    full = jnp.ones((1, 1, 1, 1), bool)    # the sp-1 "fully visible" steps

    @jax.jit
    def fwd_jnp(q, k, v, acc, m, s):
        return _block_update(q, k, v, acc, m, s, full, scale)

    @jax.jit
    def fwd_pallas(q, k, v, acc, m, s):
        return flash_block_update(q, k, v, acc, m, s, q_offset=0,
                                  k_offset=0, causal=False, scale=scale)

    def fetch3(r):
        return float(r[0].ravel()[0].astype(jnp.float32))

    t_fj = bench(fwd_jnp, (q, k, v, acc, m0, s0), iters, fetch3)
    t_fp = bench(fwd_pallas, (q, k, v, acc, m0, s0), iters, fetch3)

    # Backward step inputs: out/lse from one full-visibility update.
    acco, mo, so = fwd_jnp(q, k, v, acc, m0, s0)
    so = jnp.maximum(so, 1e-30)
    out = (acco / so.transpose(0, 2, 1)[..., None]).astype(q.dtype)
    lse = mo + jnp.log(so)
    delta = jnp.einsum("bqhd,bqhd->bqh", do, out,
                       preferred_element_type=jnp.float32)

    @jax.jit
    def bwd_jnp(q, k, v, do, lse, delta):
        # the PRODUCTION _ring_diff_bwd step body (imported, not copied
        # — ADVICE r4: an inline re-implementation can silently drift),
        # full-visibility case, no GQA (group=1).
        f32 = jnp.float32
        return _bwd_block_grads(q.astype(f32), do.astype(f32), k, v, lse,
                                delta.transpose(0, 2, 1), None, scale, 1)

    @jax.jit
    def bwd_pallas(q, k, v, do, out, lse, delta):
        return flash_grad_block(q, k, v, do, out, lse, causal=False,
                                scale=scale,
                                delta=delta.transpose(0, 2, 1))

    # Correctness gate (on-device reduce, bwd_ab.py rationale): a wrong
    # kernel must not publish a speedup.
    @jax.jit
    def rel_diff(r1, r2):
        rels = [jnp.abs(a.astype(jnp.float32) - b_.astype(jnp.float32)
                        ).max()
                / jnp.maximum(jnp.abs(a.astype(jnp.float32)).max(), 1e-9)
                for a, b_ in zip(r1, r2)]
        return jnp.stack(rels).max()

    rel = float(rel_diff(list(bwd_jnp(q, k, v, do, lse, delta)),
                         list(bwd_pallas(q, k, v, do, out, lse, delta))))
    correct = rel < 5e-2                   # bf16 inputs, f32 accumulation

    t_bj = bench(bwd_jnp, (q, k, v, do, lse, delta), iters, fetch3)
    t_bp = (bench(bwd_pallas, (q, k, v, do, out, lse, delta), iters,
                  fetch3) if correct else None)

    dev = jax.devices()[0]
    print(json.dumps({
        "metric": "ring_block_ab", "platform": dev.platform,
        "device_kind": dev.device_kind,
        "shape": {"batch": b, "local_seq": l, "heads": h, "dim": d},
        "fwd_jnp_ms": round(t_fj * 1000, 3),
        "fwd_pallas_ms": round(t_fp * 1000, 3),
        "fwd_pallas_speedup": round(t_fj / t_fp, 3),
        "bwd_rel_max_diff": rel,
        "bwd_correctness_ok": correct,
        "bwd_jnp_ms": round(t_bj * 1000, 3),
        "bwd_pallas_ms": round(t_bp * 1000, 3) if correct else None,
        "bwd_pallas_speedup": round(t_bj / t_bp, 3) if correct else None,
    }), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--local-seqs", default="2048,4096,8192")
    ap.add_argument("--iters", type=int, default=30)
    args = ap.parse_args()
    for l in [int(x) for x in args.local_seqs.split(",")]:
        run_shape(args.batch, l, args.heads, args.dim, args.iters)


if __name__ == "__main__":
    main()
