#!/bin/bash
# Wait for the tunnelled TPU to answer a real matmul, then run the A/B
# queue once.  The probe is a separate bounded subprocess because a down
# tunnel hangs jax.devices() indefinitely (measured round 3 + round 4).
cd "$(dirname "$0")/.." || exit 1
while true; do
  # nice -19: the probe hangs ~60s on a down tunnel and this box has ONE
  # core — an un-niced probe every 3 min starves concurrent pytest
  # integration tests (measured: elastic launcher phases missed their
  # 120 s progress windows only while the watcher ran).
  if nice -n 19 timeout 60 python -c "
import jax, jax.numpy as jnp
jax.devices()
float((jnp.ones((8, 8)) @ jnp.ones((8, 8))).sum())
" >/dev/null 2>&1; then
    echo "chip up at $(date -u +%FT%TZ)"
    break
  fi
  echo "chip down at $(date -u +%FT%TZ); retry in 180s"
  sleep 180
done
exec python tools/tpu_ab.py "$@"
