"""Evaluate the parked A/B decision rules against tools/ab_results.json.

The rules live in docs/performance.md ("Pending at round-4 close" +
"Round-5 additions"); this tool turns the latest measured runs into
explicit verdicts so flipping defaults is mechanical and auditable:

  * smallseq   — best lm_smallseq_hb*_bs128 vs lm_base_bs128_remat;
                 win => engage `_smallseq_enabled` auto + default HB.
  * flash_bwd  — lm_seq4096_fbwd_kernel vs _xla; win => default
                 HVDT_FLASH_BWD=kernel for 2048 <= seq < 8192.
  * xent_chunk — lm_chunk16384_bs128 vs base; win => default 16384.
  * ring       — ring_ab fwd/bwd Pallas speedups at both local shards;
                 both >1 => default HVDT_RING_PALLAS=1.
  * resnet_1x1 — pallas_vs_conv on the probe shapes; >1.05 anywhere =>
                 wire the fused kernel; else close the lever.

WIN_MARGIN = 1.02: a default only flips on a >=2% end-to-end win —
within-window variance on this chip was measured ~±0.5%
(docs/performance.md), so 2% is comfortably outside noise.
Reads ALL runs, keeps each leg's LATEST successful result.  Prints one
JSON line; exits 0 even when evidence is incomplete (verdict
"unmeasured" — the honest state, never a guess).
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WIN_MARGIN = 1.02

# Must cover tools/resnet_probe.py SHAPES exactly (kept in sync by
# tests/test_ab_decide.py; not imported — resnet_probe imports jax at
# module scope and this tool must stay dependency-free).
PROBE_SHAPES = {"s3_contract", "s3_expand", "s4_contract", "s4_expand"}


def latest_results(path):
    with open(path) as f:
        hist = json.load(f)
    latest = {}
    for run in hist:
        for r in run.get("results", []):
            if r.get("ok") and r.get("result") is not None:
                latest[r["name"]] = {"at": run.get("at"),
                                     "result": r["result"]}
    return latest


def toks(latest, name):
    entry = latest.get(name)
    if not entry:
        return None
    res = entry["result"]
    return res.get("tokens_per_sec") if isinstance(res, dict) else None


def decide(latest):
    out = {}

    base = toks(latest, "lm_base_bs128_remat")
    legs = {hb: toks(latest, f"lm_smallseq_hb{hb}_bs128")
            for hb in (4, 8, 16)}
    measured = {hb: t for hb, t in legs.items() if t}
    if base and measured:
        best_hb, best = max(measured.items(), key=lambda kv: kv[1])
        out["smallseq"] = {
            "baseline_tok_s": base, "per_hb": measured,
            "best_hb": best_hb, "best_tok_s": best,
            "speedup": round(best / base, 4),
            "verdict": ("ENGAGE_AUTO" if best >= base * WIN_MARGIN
                        else "KEEP_DISENGAGED"),
            "action": ("set _SMALLSEQ_AUTO_MIN_PROGRAMS (transformer.py) "
                       f"and default HVDT_FLASH_SMALLSEQ_HB={best_hb}"
                       if best >= base * WIN_MARGIN else
                       "record the measured loss in docs/performance.md")}
    else:
        out["smallseq"] = {"verdict": "unmeasured"}

    kern = toks(latest, "lm_seq4096_fbwd_kernel")
    xla = toks(latest, "lm_seq4096_fbwd_xla")
    if kern and xla:
        out["flash_bwd"] = {
            "kernel_tok_s": kern, "xla_tok_s": xla,
            "speedup": round(kern / xla, 4),
            "verdict": ("DEFAULT_KERNEL" if kern >= xla * WIN_MARGIN
                        else "KEEP_XLA"),
            "action": ("default HVDT_FLASH_BWD=kernel for "
                       "2048<=seq<8192 (common/config.py)"
                       if kern >= xla * WIN_MARGIN else
                       "keep HVDT_FLASH_BWD=xla; note e2e result")}
    else:
        out["flash_bwd"] = {"verdict": "unmeasured"}

    chunk = toks(latest, "lm_chunk16384_bs128")
    if chunk and base:
        out["xent_chunk"] = {
            "chunk16384_tok_s": chunk, "baseline_tok_s": base,
            "speedup": round(chunk / base, 4),
            "verdict": ("DEFAULT_16384" if chunk >= base * WIN_MARGIN
                        else "KEEP_8192")}
    else:
        out["xent_chunk"] = {"verdict": "unmeasured"}

    ring = {}
    for shard in (2048, 8192):
        entry = latest.get(f"ring_ab_local{shard}")
        if entry and isinstance(entry["result"], dict):
            r = entry["result"]
            ring[shard] = {"fwd": r.get("fwd_pallas_speedup"),
                           "bwd": r.get("bwd_pallas_speedup"),
                           "bwd_ok": r.get("bwd_correctness_ok"),
                           "platform": r.get("platform")}
    if (set(ring) == {2048, 8192}
            and all(v["platform"] == "tpu" for v in ring.values())):
        # Complete evidence only (one shard measured mid-outage is not a
        # loss — it's unmeasured); same WIN_MARGIN as every other
        # default flip — a 1.00x-1.02x "win" is within the documented
        # within-window variance.
        wins = [s for s, v in ring.items()
                if v["fwd"] and v["bwd"] and v["bwd_ok"]
                and v["fwd"] >= WIN_MARGIN and v["bwd"] >= WIN_MARGIN]
        out["ring"] = {"per_shard": ring,
                       "verdict": ("DEFAULT_RING_PALLAS"
                                   if len(wins) == 2 else "KEEP_JNP")}
    else:
        out["ring"] = {"verdict": "unmeasured",
                       **({"per_shard": ring} if ring else {})}

    out["resnet_1x1"] = _probe_verdict(latest.get("resnet_1x1_probe"))
    out["resnet_1x1_train"] = _probe_verdict(
        latest.get("resnet_1x1_train_probe"))

    def bench_img_s(name):
        entry = latest.get(name)
        if not entry or not isinstance(entry["result"], dict):
            return None, None
        r = entry["result"]
        # stale fallback headlines and CPU probes are not window
        # evidence
        if r.get("platform") != "tpu" or r.get("stale"):
            return None, None
        return r.get("value"), entry.get("at")

    base_img, base_at = bench_img_s("resnet_bench_default")
    fused_img, fused_at = bench_img_s("resnet_bench_fused")
    # Same-run only: the legs are scheduled adjacent precisely so the
    # comparison is within one measurement window — pairing a default
    # from run N with a fused from run N+1 is the cross-window
    # comparison the harness docstring forbids.
    if base_img and fused_img and base_at == fused_at is not None:
        out["resnet_e2e_fused"] = {
            "default_img_s": base_img, "fused_img_s": fused_img,
            "speedup": round(fused_img / base_img, 4),
            "verdict": ("DEFAULT_FUSED" if fused_img >= base_img
                        * WIN_MARGIN else "KEEP_XLA_CONV"),
            "action": ("default HVDT_FUSED_CONV1X1=1 (common/config.py)"
                       if fused_img >= base_img * WIN_MARGIN else
                       "keep off; record the e2e number")}
    else:
        out["resnet_e2e_fused"] = {"verdict": "unmeasured"}

    return out


def _probe_verdict(entry):
    """Shared rule for the affine and train-form 1x1 probes."""
    if not (entry and isinstance(entry["result"], list)):
        return {"verdict": "unmeasured"}
    rows = {r["shape"]: {"pallas_vs_conv": r.get("pallas_vs_conv"),
                         "matmul_vs_conv": r.get("matmul_vs_conv"),
                         "ok": r.get("correctness_ok"),
                         "platform": r.get("platform")}
            for r in entry["result"]}
    # platform gate: interpret-mode CPU rows are complete and
    # correctness-pass but time nothing real — only chip rows may
    # feed a permanent verdict (the bench.py last-good discipline).
    measured = {s for s, v in rows.items()
                if v["ok"] and v["pallas_vs_conv"]
                and v["platform"] == "tpu"}
    if measured != PROBE_SHAPES:
        # CLOSE_LEVER is permanent — it may only come from a FULL
        # probe (every shape correctness-passed AND Pallas-timed);
        # a crashed or miscomparing run stays "unmeasured".
        return {"verdict": "unmeasured", "per_shape": rows,
                "missing": sorted(PROBE_SHAPES - measured)}
    wins = sorted(s for s in measured
                  if rows[s]["pallas_vs_conv"] > 1.05)
    return {"per_shape": rows,
            "verdict": "WIRE_FUSED_KERNEL" if wins else "CLOSE_LEVER",
            "winning_shapes": wins}


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        REPO, "tools", "ab_results.json")
    latest = latest_results(path)
    print(json.dumps({"decisions": decide(latest),
                      "legs_seen": sorted(latest)}, indent=1))


if __name__ == "__main__":
    main()
