#!/usr/bin/env python
"""Regenerate the static cost-model calibration from measured rows.

Reads any set of ``bench_allreduce.py --json-out`` result files
(normalized row schema, ``schema_version`` >= 1; legacy files are
adapted), fits the per-(tier, algorithm, wire) alpha-beta constants via
``horovod_tpu.analysis.costmodel.fit_from_bench``, and writes the
calibration JSON the model loads (default
``.hvdt-costmodel-calibration.json`` at the repo root, the
``HVDT_COSTMODEL_CALIBRATION`` default).

The checked-in calibration was fitted from the CPU-sim sweeps under
``tools/calibration/``::

    python tools/fit_costmodel.py tools/calibration/*.json

Re-run on a real TPU slice to calibrate against hardware — the sweep
commands are recorded in each row file's ``meta``/CLI echo and in
docs/analysis.md.  No jax import; safe anywhere.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from horovod_tpu.analysis import costmodel  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Fit the analysis cost-model calibration from "
                    "bench_allreduce --json-out row files.")
    ap.add_argument("rows", nargs="+",
                    help="bench_allreduce.py --json-out result files")
    ap.add_argument("--out", default=os.path.join(
        _REPO, costmodel.CALIBRATION_NAME),
        help="calibration file to write (default: the checked-in "
             "repo-root file)")
    args = ap.parse_args(argv)

    all_rows = []
    sources = []
    measured = None
    for path in args.rows:
        with open(path) as fh:
            doc = json.load(fh)
        rows = costmodel.normalize_rows(doc)
        if not rows:
            print(f"fit_costmodel: {path}: no usable rows, skipped",
                  file=sys.stderr)
            continue
        # Record the measured hierarchical-vs-flat verdict (prefer the
        # pure-f32 sweep) — the --perf gate's model-vs-measured
        # validation target.
        peak = (doc.get("hierarchical_speedup_vs_flat_at_peak")
                if isinstance(doc, dict) else None)
        if peak and (measured is None
                     or "int8" in str(measured.get("transport", ""))):
            measured = {
                "value": float(peak),
                "at_bytes": int(doc.get("at_bytes", 0) or 0),
                "mesh": doc.get("mesh", {}),
                "transport": doc.get("transport", ""),
                "file": os.path.relpath(path, _REPO),
            }
        all_rows.extend(rows)
        sources.append({
            "file": os.path.relpath(path, _REPO),
            "rows": len(rows),
            "metric": doc.get("metric") if isinstance(doc, dict) else None,
            "platform": (doc.get("platform")
                         if isinstance(doc, dict) else None),
            "n_devices": (doc.get("n_devices")
                          if isinstance(doc, dict) else None),
        })
    if not all_rows:
        print("fit_costmodel: no rows in any input file", file=sys.stderr)
        return 1

    meta = {"sources": sources}
    if measured:
        meta["measured_hier_speedup"] = measured
    cal = costmodel.fit_from_bench(all_rows, meta=meta)
    cal.save(args.out)
    print(f"fit_costmodel: {cal.describe()}")
    print(f"fit_costmodel: wrote {args.out} "
          f"({len(all_rows)} rows from {len(sources)} file(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
