"""XPlane step profiler: where does a jitted TPU step spend its time?

Captures a ``jax.profiler.trace`` of a few training steps and aggregates
the device plane's XLA-op events into a per-op/per-category table —
the TPU analog of the reference's NVTX+nvprof workflow (ref:
horovod/common/nvtx/nvtx_op_range.h + docs/timeline.rst describe the
same "which op eats the step" question for CUDA).

Usage:
  python tools/profile_step.py --model resnet --batch-size 128 --steps 3
  python tools/profile_step.py --model lm --batch-size 8 --steps 3

The parser is generic: ``aggregate(xplane_path)`` works on any capture
(the proto comes from tensorflow.tsl, present in this image; jax writes
the .xplane.pb file).
"""

from __future__ import annotations

import argparse
import collections
import glob
import os
import sys
import tempfile

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__),
                                                os.pardir)))


def capture(fn, steps: int, trace_dir: str | None = None) -> str:
    """Run ``fn()`` ``steps`` times under the profiler; return the
    .xplane.pb path. ``fn`` must end with a host fetch so device work for
    each step is inside the trace window."""
    import jax

    trace_dir = trace_dir or tempfile.mkdtemp(prefix="hvdt_trace_")
    with jax.profiler.trace(trace_dir):
        for _ in range(steps):
            fn()
    paths = sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                             recursive=True))
    if not paths:
        raise RuntimeError(f"no .xplane.pb under {trace_dir}")
    return paths[-1]


def _load_planes(xplane_path: str):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    space = xplane_pb2.XSpace()
    with open(xplane_path, "rb") as f:
        space.ParseFromString(f.read())
    return space.planes


def aggregate(xplane_path: str, device_substr: str = "TPU"):
    """Aggregate device-plane events: returns (per_op, per_category,
    busy_ps, span_ps) where per_op maps op name ->
    dict(dur_ps, count, category, bytes_accessed)."""
    planes = _load_planes(xplane_path)
    dev = None
    for p in planes:
        if device_substr in p.name and "Host" not in p.name:
            # Prefer the op-level plane (has XLA op events).
            if dev is None or len(p.lines) > len(dev.lines):
                dev = p
    if dev is None:
        # CPU-sim fallback: jax's CPU profiler puts XLA op events on the
        # '/host:CPU' plane (there is no separate device plane).
        for p in planes:
            if p.name == "/host:CPU":
                dev = p
                break
    if dev is None:
        raise RuntimeError(
            f"no device plane matching {device_substr!r}; planes: "
            f"{[p.name for p in planes]}")

    stat_names = {m.id: m.name for m in dev.stat_metadata.values()}
    ev_meta = {m.id: m for m in dev.event_metadata.values()}

    per_op = collections.defaultdict(
        lambda: {"dur_ps": 0, "count": 0, "category": "",
                 "bytes_accessed": 0})
    span_lo, span_hi = None, 0
    # Only aggregate op-level lines; module-level lines double-count.
    op_lines = [ln for ln in dev.lines
                if "XLA Op" in ln.name or "XLA Ops" in ln.name]
    if not op_lines:
        op_lines = list(dev.lines)
    for ln in op_lines:
        for ev in ln.events:
            md = ev_meta.get(ev.metadata_id)
            name = md.name if md else f"op_{ev.metadata_id}"
            rec = per_op[name]
            rec["dur_ps"] += ev.duration_ps
            rec["count"] += 1
            lo = ev.offset_ps
            hi = ev.offset_ps + ev.duration_ps
            span_lo = lo if span_lo is None else min(span_lo, lo)
            span_hi = max(span_hi, hi)
            stats = list(ev.stats) + (list(md.stats) if md else [])
            for st in stats:
                sname = stat_names.get(st.metadata_id, "")
                if sname in ("hlo_category", "category"):
                    rec["category"] = (st.str_value
                                       or rec["category"])
                elif sname in ("bytes_accessed", "bytes accessed"):
                    rec["bytes_accessed"] += (st.uint64_value
                                              or st.int64_value)
    per_cat = collections.defaultdict(lambda: {"dur_ps": 0, "count": 0})
    busy = 0
    for name, rec in per_op.items():
        cat = rec["category"] or _guess_category(name)
        per_cat[cat]["dur_ps"] += rec["dur_ps"]
        per_cat[cat]["count"] += rec["count"]
        rec["category"] = cat
        busy += rec["dur_ps"]
    span = (span_hi - (span_lo or 0)) if span_hi else 0
    return dict(per_op), dict(per_cat), busy, span


def _guess_category(name: str) -> str:
    n = name.lower()
    for key, cat in (("conv", "convolution"), ("fusion", "fusion"),
                     ("dot", "dot"), ("copy", "copy"),
                     ("all-reduce", "collective"),
                     ("reduce", "reduce"), ("transpose", "transpose")):
        if key in n:
            return cat
    return "other"


def report(per_op, per_cat, busy_ps, span_ps, steps: int, top: int = 25,
           out=sys.stdout):
    def pct(x):
        return 100.0 * x / busy_ps if busy_ps else 0.0

    print(f"trace span {span_ps / 1e9:.2f} ms, device busy "
          f"{busy_ps / 1e9:.2f} ms "
          f"({100.0 * busy_ps / span_ps if span_ps else 0:.1f}% occupancy), "
          f"{steps} steps -> {busy_ps / 1e9 / steps:.2f} ms busy/step",
          file=out)
    print("\nby category:", file=out)
    for cat, rec in sorted(per_cat.items(), key=lambda kv: -kv[1]["dur_ps"]):
        print(f"  {cat:<22} {rec['dur_ps'] / 1e9:8.2f} ms "
              f"{pct(rec['dur_ps']):5.1f}%  n={rec['count']}", file=out)
    print(f"\ntop {top} ops:", file=out)
    for name, rec in sorted(per_op.items(),
                            key=lambda kv: -kv[1]["dur_ps"])[:top]:
        extra = (f" bytes={rec['bytes_accessed'] / 1e6:.0f}MB"
                 if rec["bytes_accessed"] else "")
        print(f"  {rec['dur_ps'] / 1e9:8.2f} ms {pct(rec['dur_ps']):5.1f}% "
              f"x{rec['count']:<4} [{rec['category']}] {name[:90]}{extra}",
              file=out)


def _build_resnet_step(batch_size: int):
    import functools

    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu.models import (ResNetConfig, resnet50_init,
                                    resnet_loss)

    cfg = ResNetConfig(num_classes=1000, dtype=jnp.bfloat16)
    params, stats = resnet50_init(jax.random.PRNGKey(0), cfg)
    opt = optax.sgd(0.01, momentum=0.9)
    opt_state = opt.init(params)
    images = jax.random.normal(jax.random.PRNGKey(1),
                               (batch_size, 224, 224, 3), jnp.bfloat16)
    labels = jax.random.randint(jax.random.PRNGKey(2), (batch_size,),
                                0, 1000)

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(params, stats, opt_state, images, labels):
        (loss, new_stats), grads = jax.value_and_grad(
            resnet_loss, has_aux=True)(params, stats, images, labels, cfg)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_stats, opt_state, \
            loss

    state = [params, stats, opt_state]

    def run_one():
        p, s, o, loss = step(state[0], state[1], state[2], images, labels)
        state[0], state[1], state[2] = p, s, o
        float(loss)   # host fetch: device completion inside the window

    # warmup/compile outside the trace
    run_one()
    return run_one


def _build_lm_step(batch_size: int):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    "examples"))
    raise SystemExit("lm profiling: use examples/jax_transformer_lm.py "
                     "--profile instead")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet", choices=["resnet", "lm"])
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--xplane", help="skip capture; parse this file")
    args = ap.parse_args()

    if args.xplane:
        path = args.xplane
    else:
        fn = (_build_resnet_step(args.batch_size) if args.model == "resnet"
              else _build_lm_step(args.batch_size))
        path = capture(fn, args.steps)
        print(f"xplane: {path}", file=sys.stderr)
    per_op, per_cat, busy, span = aggregate(path)
    report(per_op, per_cat, busy, span, args.steps, args.top)


if __name__ == "__main__":
    main()
