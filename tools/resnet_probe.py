"""Below-XLA ResNet roofline probe (VERDICT r4 weak #3 closure).

The bs128 ResNet-50 step is HBM-roofline-pinned (hbm_util 1.0,
docs/performance.md); round 2 named two residual traffic levers — conv
layout copies and unfused BN passes — and neither was ever measured
beneath XLA.  This probe measures ONE lever end-to-end on the real chip:
for the bottleneck blocks' hot 1x1 convs (the matmul-shaped majority of
ResNet-50 conv FLOPs), does a Pallas matmul with the BN affine fused
into its epilogue (ops/conv_fused.py) move fewer HBM bytes than XLA's
scheduling of the same conv + affine + relu?

Three legs per shape, one within-window comparison (docs/performance.md
discipline):
  * xla_conv   — lax.conv_general_dilated NHWC + affine + relu, jitted
                 (the production path's shape: models/resnet.py _conv ->
                 _batch_norm normalized form -> relu)
  * xla_matmul — the same math expressed as reshape+dot, jitted (strips
                 any conv-layout handling; isolates the layout lever
                 from the fusion lever)
  * pallas     — ops/conv_fused.matmul_bn_relu (single fused write)

Timing follows the repo contract: each timed region ends with a host
fetch of a scalar that data-depends on the last result
(block_until_ready is a no-op over the tunnel); >=30 calls per region.
Correctness-gates Pallas against the f32 reference before timing —
a wrong kernel must not publish a speedup.  Prints one JSON line per
shape with ms/call, effective GB/s, and the speedup ratios.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu.ops.conv_fused import (conv1x1_bn_relu,
                                        conv1x1_bn_relu_reference,
                                        conv1x1_bn_train,
                                        conv1x1_bn_train_reference,
                                        matmul_bn_relu)

# The four hot 1x1 shapes of bs128 ResNet-50 stages 3/4 (NHWC,
# models/resnet.py bottleneck conv1/conv3; stage-2's 64-channel convs
# are excluded — N=64 is below the 128-lane tile).
SHAPES = [
    ("s3_contract", 128, 28, 28, 512, 128),
    ("s3_expand", 128, 28, 28, 128, 512),
    ("s4_contract", 128, 14, 14, 1024, 256),
    ("s4_expand", 128, 14, 14, 256, 1024),
]


def bench(f, args_, iters):
    r = f(*args_)                      # compile + first run
    float(jnp.sum(r[0, 0]))            # sync
    t0 = time.perf_counter()
    for _ in range(iters):
        r = f(*args_)
    float(jnp.sum(r[0, 0]))            # host fetch ends the region
    return (time.perf_counter() - t0) / iters


def run_shape(label, b, h, w_, cin, cout, iters):
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    x = jax.random.normal(ks[0], (b, h, w_, cin), jnp.bfloat16)
    w = jax.random.normal(ks[1], (cin, cout), jnp.bfloat16) * (cin ** -0.5)
    scale = jax.random.uniform(ks[2], (cout,), jnp.float32, 0.5, 1.5)
    bias = jax.random.normal(ks[3], (cout,), jnp.float32)

    @jax.jit
    def xla_conv(x, w, scale, bias):
        y = lax.conv_general_dilated(
            x, w.reshape(1, 1, cin, cout), (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return jnp.maximum(y * scale + bias, 0.0).astype(x.dtype)

    @jax.jit
    def xla_matmul(x, w, scale, bias):
        y = jnp.dot(x.reshape(b * h * w_, cin), w,
                    preferred_element_type=jnp.float32)
        y = jnp.maximum(y * scale + bias, 0.0)
        return y.reshape(b, h, w_, cout).astype(x.dtype)

    @jax.jit
    def pallas(x, w, scale, bias):
        return conv1x1_bn_relu(x, w, scale, bias)

    # Correctness gate (on-device reduce; bf16 inputs, f32 accumulation).
    ref = conv1x1_bn_relu_reference(x, w, scale, bias)

    @jax.jit
    def rel(a, r):
        af, rf = a.astype(jnp.float32), r.astype(jnp.float32)
        return jnp.abs(af - rf).max() / jnp.maximum(jnp.abs(rf).max(), 1e-9)

    rels = {n: float(rel(f(x, w, scale, bias), ref))
            for n, f in (("xla_conv", xla_conv), ("xla_matmul", xla_matmul),
                         ("pallas", pallas))}
    ok = all(v < 2e-2 for v in rels.values())

    t = {n: bench(f, (x, w, scale, bias), iters)
         for n, f in (("xla_conv", xla_conv), ("xla_matmul", xla_matmul),
                      *((("pallas", pallas),) if ok else ()))}

    m = b * h * w_
    bytes_min = 2 * (m * cin + cin * cout + m * cout) + 8 * cout
    dev = jax.devices()[0]
    out = {"metric": "resnet_1x1_bn_probe", "shape": label,
           "platform": dev.platform, "device_kind": dev.device_kind,
           "m_k_n": [m, cin, cout], "iters": iters,
           "correctness_ok": ok, "rel_max_diff": rels,
           "min_traffic_mb": round(bytes_min / 2 ** 20, 1)}
    for n, dt in t.items():
        out[f"{n}_ms"] = round(dt * 1e3, 3)
        out[f"{n}_eff_gbps"] = round(bytes_min / dt / 1e9, 1)
    if ok:
        out["pallas_vs_conv"] = round(t["xla_conv"] / t["pallas"], 3)
        out["pallas_vs_matmul"] = round(t["xla_matmul"] / t["pallas"], 3)
        out["matmul_vs_conv"] = round(t["xla_conv"] / t["xla_matmul"], 3)
    print(json.dumps(out), flush=True)


def run_shape_train(label, b, h, w_, cin, cout, iters):
    """TRAIN-form leg: batch-stat BN forces (at least) two reads of the
    conv output under XLA; the fused kernel emits z + stat partials in
    one pass (ops/conv_fused.matmul_batch_stats) so z is read once."""
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    x = jax.random.normal(ks[0], (b, h, w_, cin), jnp.bfloat16)
    w = jax.random.normal(ks[1], (cin, cout), jnp.bfloat16) * (cin ** -0.5)
    gamma = jax.random.uniform(ks[2], (cout,), jnp.float32, 0.5, 1.5)
    beta = jax.random.normal(ks[3], (cout,), jnp.float32)
    eps = 1e-5

    @jax.jit
    def xla_train(x, w, gamma, beta):
        z = lax.conv_general_dilated(
            x, w.reshape(1, 1, cin, cout), (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        zf = z.astype(jnp.float32)
        mean = zf.mean(axis=(0, 1, 2))
        var = zf.var(axis=(0, 1, 2))
        y = (zf - mean) * lax.rsqrt(var + eps) * gamma + beta
        return jnp.maximum(y, 0.0).astype(x.dtype), mean, var

    @jax.jit
    def pallas_train(x, w, gamma, beta):
        return conv1x1_bn_train(x, w, gamma, beta, eps=eps)

    ref = conv1x1_bn_train_reference(x, w, gamma, beta, eps=eps)

    @jax.jit
    def rel(out, r):
        rels = [jnp.abs(a.astype(jnp.float32) - b_.astype(jnp.float32)
                        ).max()
                / jnp.maximum(jnp.abs(b_.astype(jnp.float32)).max(), 1e-9)
                for a, b_ in zip(out, r)]
        return jnp.stack(rels).max()

    rels = {n: float(rel(list(f(x, w, gamma, beta)), list(ref)))
            for n, f in (("xla_train", xla_train),
                         ("pallas_train", pallas_train))}
    # bf16 z-write rounding bounds the fused y at ~1e-2 rel
    ok = all(v < 2e-2 for v in rels.values())

    # Time the y output only (y data-depends on mean/var, so the stats
    # cannot be dead-code-eliminated); bench's fetch needs an array.
    xla_y = jax.jit(lambda *a: xla_train(*a)[0])
    pallas_y = jax.jit(lambda *a: pallas_train(*a)[0])
    t = {n: bench(f, (x, w, gamma, beta), iters)
         for n, f in (("xla_train", xla_y),
                      *((("pallas_train", pallas_y),) if ok else ()))}

    m = b * h * w_
    bytes_min = 2 * (m * cin + cin * cout + 2 * m * cout) + 12 * cout
    dev = jax.devices()[0]
    out = {"metric": "resnet_1x1_bn_train_probe", "shape": label,
           "platform": dev.platform, "device_kind": dev.device_kind,
           "m_k_n": [m, cin, cout], "iters": iters,
           "correctness_ok": ok, "rel_max_diff": rels,
           "min_traffic_mb": round(bytes_min / 2 ** 20, 1)}
    for n, dt in t.items():
        out[f"{n}_ms"] = round(dt * 1e3, 3)
        out[f"{n}_eff_gbps"] = round(bytes_min / dt / 1e9, 1)
    if ok:
        out["pallas_vs_conv"] = round(t["xla_train"] / t["pallas_train"], 3)
    print(json.dumps(out), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--shapes", default=",".join(s[0] for s in SHAPES))
    ap.add_argument("--form", choices=("affine", "train"),
                    default="affine")
    args = ap.parse_args()
    want = set(args.shapes.split(","))
    run = run_shape if args.form == "affine" else run_shape_train
    for spec in SHAPES:
        if spec[0] in want:
            run(*spec, iters=args.iters)


if __name__ == "__main__":
    main()
