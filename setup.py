"""Wheel build with the native core included.

The reference drives per-framework CMake builds from setup.py
(ref: setup.py:31-66 CMakeExtension/custom_build_ext); here the single
native artifact is libhvdt_core.so from native/Makefile (plain g++, no
pybind11 — the Python side binds via ctypes).  The build is best-effort:
a wheel built where no toolchain exists still works, because the loader
(horovod_tpu/native/__init__.py) can rebuild from an sdist checkout or
fall back to pure-Python implementations.
"""

import os
import shutil
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py

HERE = os.path.dirname(os.path.abspath(__file__))


class BuildPyWithNative(build_py):
    def run(self):
        super().run()
        native_dir = os.path.join(HERE, "native")
        so = os.path.join(native_dir, "libhvdt_core.so")
        try:
            subprocess.run(["make", "-C", native_dir], check=True,
                           capture_output=True, timeout=300)
        except (OSError, subprocess.SubprocessError) as e:
            print(f"warning: native core build skipped ({e}); "
                  "the wheel will use pure-Python fallbacks")
            return
        dest = os.path.join(self.build_lib, "horovod_tpu", "native", "_lib")
        os.makedirs(dest, exist_ok=True)
        shutil.copy2(so, dest)


setup(cmdclass={"build_py": BuildPyWithNative})
