#!/bin/sh
# Emit the docker-compose test matrix as one runnable command per
# service (ref: .buildkite/gen-pipeline.sh — the reference generates its
# Buildkite pipeline the same way).  Usage: ci/gen-matrix.sh | sh -x
#
#   ci/gen-matrix.sh --smoke   emit only the fast smoke service
#       (compileall + optimizer-kernel + serving-subsystem +
#       quantized-collective + sub-byte-wire/fp8-lowbit +
#       resilience-chaos + telemetry +
#       tracing/flight-recorder-forensics + overlap-scheduling +
#       transport-policy/hierarchical-collective +
#       zero-sharding/reduce-scatter-wire +
#       pod-granular-elastic/multipod-recovery +
#       continuous-goodput/async-checkpoint/peer-restore +
#       elastic-serving-control-plane/router/autoscaler +
#       static-analysis/schedule-fingerprint +
#       static-cost-model/perf-gate +
#       live-attribution/time-series/anomaly-detection +
#       continuous-batching-llm-serve (paged KV / scheduler /
#       prefix-sharing / ring-prefill) +
#       closed-loop-policy-controller (pricing / guardrails /
#       leg-actuation / driver-hook) +
#       fleet-scheduler (shared inventory / seq-guarded target doc /
#       bin-packing reclaim-backfill / trace-driven chaos sim) +
#       4d-parallel (pp*ep*dp acceptance vs 1-chip dense reference /
#       priced-vs-observed pipeline bubble / int8 expert wire /
#       layout-change checkpoint restore) tests on
#       CPU) — the pre-merge gate.  The full matrix additionally
#       emits the `analysis` service: python -m horovod_tpu.analysis
#       --all --perf as a hard gate over the hvdt-lint ratchet
#       baseline AND the .hvdt-perf-baseline.json perf ratchet
#       (model-predicted exposed-comm seconds / wire bytes / overlap
#       fraction of the reference fingerprints).
set -eu
only=""
if [ "${1:-}" = "--smoke" ]; then
  only="test-smoke"
  shift
fi
compose=${1:-docker-compose.test.yml}
for svc in $(sed -n 's/^  \([a-z0-9-]*\):$/\1/p' "$compose"); do
  if [ -n "$only" ] && [ "$svc" != "$only" ]; then
    continue
  fi
  echo "docker compose -f $compose run --rm $svc"
done
