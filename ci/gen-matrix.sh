#!/bin/sh
# Emit the docker-compose test matrix as one runnable command per
# service (ref: .buildkite/gen-pipeline.sh — the reference generates its
# Buildkite pipeline the same way).  Usage: ci/gen-matrix.sh | sh -x
set -eu
compose=${1:-docker-compose.test.yml}
for svc in $(sed -n 's/^  \([a-z0-9-]*\):$/\1/p' "$compose"); do
  echo "docker compose -f $compose run --rm $svc"
done
