"""ImageNet ResNet-50 training — the reference's headline workload.

Re-conception of ref: examples/pytorch/pytorch_imagenet_resnet50.py —
same program shape: warmup+staircase LR schedule scaled by world size,
DistributedOptimizer with optional bf16 wire compression, rank-0
checkpointing with broadcast-on-restart, per-epoch metric averaging.

TPU-native: bf16 compute, NHWC layout, jitted shard_map step over the
'dp' mesh axis, device prefetch of the input pipeline.  Real data plugs
in via --train-dir with `.npy` shards (or swap `synthetic_batches` for a
tf.data/grain pipeline); without it the script runs on synthetic data so
the full loop (schedule, checkpoint, metrics) is exercisable anywhere.
"""

import argparse
import os
import time

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--train-dir", default=None,
                   help="directory of {images,labels}_*.npy shards")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-device batch size")
    p.add_argument("--base-lr", type=float, default=0.0125,
                   help="LR for a single device (scaled by world size)")
    p.add_argument("--warmup-epochs", type=float, default=5)
    p.add_argument("--steps-per-epoch", type=int, default=20,
                   help="synthetic-mode steps per epoch")
    p.add_argument("--checkpoint", default="/tmp/resnet50_ckpt.npz")
    p.add_argument("--fp16-allreduce", action="store_true")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.callbacks import warmup_schedule
    from horovod_tpu.data import prefetch_to_device
    from horovod_tpu.models import ResNetConfig, resnet50_init, resnet_loss

    hvd.init()
    mesh = hvd.mesh()
    n_dev = mesh.devices.size
    global_batch = args.batch_size * n_dev

    cfg = ResNetConfig(num_classes=1000, dtype=jnp.bfloat16)
    params, stats = resnet50_init(jax.random.PRNGKey(0), cfg)

    # Linear-warmup then staircase decay, scaled by world size
    # (ref: pytorch_imagenet_resnet50.py adjust_learning_rate).
    steps_per_epoch = args.steps_per_epoch
    staircase = optax.piecewise_constant_schedule(
        args.base_lr * n_dev,
        {int(e * steps_per_epoch): d for e, d in ((30, 0.1), (60, 0.1),
                                                  (80, 0.1))})
    sched = warmup_schedule(base_lr=args.base_lr, scale=n_dev,
                            warmup_steps=int(args.warmup_epochs
                                             * steps_per_epoch),
                            after=staircase)
    opt = hvd.DistributedOptimizer(
        optax.sgd(sched, momentum=0.9),
        compression=(hvd.Compression.bf16 if args.fp16_allreduce
                     else hvd.Compression.none))
    opt_state = opt.init(params)

    # Resume: rank 0 loads, everyone receives via broadcast
    # (ref: checkpoint-broadcast pattern, SURVEY.md §5.4).
    start_epoch = 0
    if os.path.exists(args.checkpoint) and hvd.rank() == 0:
        ck = np.load(args.checkpoint, allow_pickle=True)
        flat = list(ck["params"])
        params = jax.tree.unflatten(jax.tree.structure(params), flat)
        start_epoch = int(ck["epoch"])
        print(f"resumed from {args.checkpoint} at epoch {start_epoch}")
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt_state = hvd.broadcast_optimizer_state(opt_state, root_rank=0)
    start_epoch = int(np.asarray(hvd.broadcast(
        np.int64(start_epoch), root_rank=0, name="start_epoch")))

    def local_step(params, stats, opt_state, x, y):
        def loss_fn(p):
            loss, new_stats = resnet_loss(p, stats, x, y, cfg)
            return loss, new_stats

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # Cross-replica running-stat averaging (SyncBatchNorm analog).
        new_stats = jax.tree.map(lambda s: jax.lax.pmean(s, "dp"), new_stats)
        return params, new_stats, opt_state, jax.lax.pmean(loss, "dp")

    # donated_step: params/stats/opt-state buffers donated through the
    # pipeline + the persistent compilation cache engaged when
    # HVDT_COMPILATION_CACHE names a directory.
    step = hvd.donated_step(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), P(), P(), P("dp"), P("dp")),
        out_specs=(P(), P(), P(), P())),
        donate_argnums=(0, 1, 2))

    def synthetic_batches(n):
        rng = np.random.default_rng(1)
        for _ in range(n):
            yield (rng.normal(size=(global_batch, 224, 224, 3))
                   .astype(np.float32),
                   rng.integers(0, 1000, global_batch).astype(np.int32))

    def disk_batches():
        import glob

        files = sorted(glob.glob(os.path.join(args.train_dir,
                                              "images_*.npy")))
        for f in files:
            images = np.load(f)
            labels = np.load(f.replace("images_", "labels_"))
            for s in range(len(images) // global_batch):
                sl = slice(s * global_batch, (s + 1) * global_batch)
                yield images[sl], labels[sl]

    sharding = NamedSharding(mesh, P("dp"))
    for epoch in range(start_epoch, args.epochs):
        t0 = time.perf_counter()
        batches = (disk_batches() if args.train_dir
                   else synthetic_batches(steps_per_epoch))
        n_steps = 0
        for xb, yb in prefetch_to_device(batches, size=2,
                                         sharding=sharding):
            params, stats, opt_state, loss = step(params, stats, opt_state,
                                                  xb, yb)
            n_steps += 1
        # Host fetch, not block_until_ready (a no-op on some tunnelled
        # PJRT backends) — the timed epoch must cover real device work.
        float(loss)
        dt = time.perf_counter() - t0
        rate = n_steps * global_batch / dt
        avg_loss = float(np.asarray(hvd.allreduce(
            np.float32(loss), name="epoch_loss")))
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={avg_loss:.4f} "
                  f"{rate:.1f} img/sec ({rate / n_dev:.1f}/device)")
            flat = [np.asarray(l) for l in jax.tree.leaves(params)]
            np.savez(args.checkpoint, params=np.array(flat, dtype=object),
                     epoch=epoch + 1)

    if hvd.rank() == 0:
        print("training complete.")


if __name__ == "__main__":
    main()
