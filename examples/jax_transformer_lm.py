"""Transformer LM pretraining with hybrid parallelism — the flagship demo.

Beyond-reference capability (SURVEY.md §2.7: the reference is DP-only;
this framework's substrate expresses tp/sp/pp/ep natively): one script
that trains the Transformer LM over a 5-axis mesh — data (dp), tensor
(tp), sequence/ring-attention (sp), pipeline (pp), expert (ep) — with the
dp gradient allreduce riding the same fused-collective machinery as every
other example.

CPU simulation of an 8-chip slice:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/jax_transformer_lm.py --dp 2 --tp 2 --pp 2 --steps 5
"""

import argparse
import time

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--pp", type=int, default=1)
    p.add_argument("--sp", type=int, default=1)
    p.add_argument("--ep", type=int, default=1)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--d-ff", type=int, default=512)
    p.add_argument("--vocab", type=int, default=1024)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--batch", type=int, default=8,
                   help="global batch (must divide by dp*pp)")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--lr", type=float, default=3e-4)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import optax
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.models import (TransformerConfig, transformer_init,
                                    transformer_logical_axes,
                                    transformer_loss,
                                    transformer_flops_per_token)
    from horovod_tpu.parallel import (make_mesh, logical_to_mesh,
                                      transformer_rules)

    hvd.init()
    need = args.dp * args.tp * args.pp * args.sp * args.ep
    devs = jax.devices()
    assert len(devs) >= need, f"need {need} devices, have {len(devs)}"
    mesh = make_mesh(devices=devs[:need], dp=args.dp, tp=args.tp,
                     pp=args.pp, sp=args.sp, ep=args.ep)

    cfg = TransformerConfig(
        vocab=args.vocab, layers=args.layers, d_model=args.d_model,
        heads=args.heads, kv_heads=args.heads, d_ff=args.d_ff,
        max_seq=args.seq, dtype=jnp.float32,
        num_experts=2 * args.ep if args.ep > 1 else 0,
        sp=args.sp, ep=args.ep, pp=args.pp)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    rules = transformer_rules()
    axes = transformer_logical_axes(cfg)

    opt = optax.adamw(args.lr)
    opt_state = opt.init(params)

    # Map stacked-param dims onto manual mesh axes — only axes of size > 1
    # (a size-1 mapping would make params VMA-varying while activations
    # stay invariant, tripping the scan carry type check).
    manual_map = {}
    if args.pp > 1:
        manual_map["stages"] = "pp"
    if args.ep > 1:
        manual_map["experts"] = "ep"

    def manual_spec(tree):
        def keep(lg):
            spec = [manual_map.get(name) for name in lg]
            while spec and spec[-1] is None:
                spec.pop()
            return P(*spec)
        return jax.tree.map(
            keep, tree,
            is_leaf=lambda x: isinstance(x, tuple) and all(
                isinstance(e, (str, type(None))) for e in x))

    def _local_loss(p, t):
        l = transformer_loss(p, t, cfg)
        varying = tuple(set(jax.typeof(l).vma) & {"pp", "sp", "ep"})
        return lax.pmean(l, varying) if varying else l

    island = jax.shard_map(
        _local_loss, mesh=mesh,
        in_specs=(manual_spec(axes), P(None, "sp")),
        out_specs=P(), axis_names={"pp", "sp", "ep"})

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(island)(params, tokens)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # Parameter shardings from logical-axis rules (tp/pp/ep placement).
    param_sh = jax.tree.map(
        lambda lg: NamedSharding(mesh, logical_to_mesh(lg, rules, mesh)),
        axes,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            isinstance(e, (str, type(None))) for e in x))
    params = jax.device_put(params, param_sh)
    step = jax.jit(train_step, donate_argnums=(0, 1))

    rng = np.random.default_rng(0)
    tok_sharding = NamedSharding(mesh, P("dp", "sp"))

    def batch():
        t = rng.integers(0, args.vocab, (args.batch, args.seq),
                         dtype=np.int64).astype(np.int32)
        return jax.device_put(t, tok_sharding)

    # Warmup/compile
    params, opt_state, loss = step(params, opt_state, batch())
    jax.block_until_ready(loss)
    first = float(loss)

    t0 = time.perf_counter()
    for _ in range(args.steps):
        params, opt_state, loss = step(params, opt_state, batch())
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    tokens_sec = args.steps * args.batch * args.seq / dt
    tflops = (3 * transformer_flops_per_token(cfg) * tokens_sec) / 1e12
    if hvd.rank() == 0:
        print(f"mesh={dict(mesh.shape)}")
        print(f"loss: {first:.4f} -> {float(loss):.4f}")
        print(f"{tokens_sec:.0f} tokens/sec, ~{tflops:.3f} model TFLOP/s")
        assert float(loss) < first, "loss should decrease"
        print("done.")


if __name__ == "__main__":
    main()
