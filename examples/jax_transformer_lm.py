"""Transformer LM pretraining with hybrid parallelism — the flagship demo.

Beyond-reference capability (SURVEY.md §2.7: the reference is DP-only;
this framework's substrate expresses tp/sp/pp/ep natively): one script
that trains the Transformer LM over a 5-axis mesh — data (dp), tensor
(tp), sequence/ring-attention (sp), pipeline (pp), expert (ep) — with the
dp gradient allreduce riding the same fused-collective machinery as every
other example.

CPU simulation of an 8-chip slice:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/jax_transformer_lm.py --dp 2 --tp 2 --pp 2 --steps 5
"""

import argparse
import contextlib
import os
import time

import numpy as np


PRESETS = {
    # BASELINE.md config 4: BERT-Large-scale pretraining (340M params).
    # The LM objective here is causal rather than MLM; the capability
    # under test — Adasum + wire compression + fused dp allreduce at
    # 24x1024x16 scale — is objective-agnostic.
    "bert-large": dict(layers=24, d_model=1024, heads=16, d_ff=4096,
                       seq=512, vocab=30528, remat=True, loss_chunk=8192),
}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--pp", type=int, default=1)
    p.add_argument("--sp", type=int, default=1)
    p.add_argument("--ep", type=int, default=1)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--d-ff", type=int, default=512)
    p.add_argument("--vocab", type=int, default=1024)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--batch", type=int, default=8,
                   help="global batch (must divide by dp*pp)")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--preset", choices=sorted(PRESETS), default=None,
                   help="named model scale (overrides size flags)")
    p.add_argument("--dtype", choices=["float32", "bfloat16"],
                   default="float32",
                   help="activation/compute dtype (bfloat16 on TPU)")
    p.add_argument("--remat", action="store_true",
                   help="jax.checkpoint each block (trade FLOPs for HBM)")
    p.add_argument("--no-remat", action="store_true",
                   help="force remat OFF even when a preset enables it "
                        "(drops the 4/3 recompute; needs the "
                        "activations to fit in HBM — small batch)")
    p.add_argument("--remat-policy", choices=["full", "dots"],
                   default="full",
                   help="full: recompute the whole block; dots: save "
                        "matmul outputs, recompute only elementwise "
                        "(more HBM, no MXU recompute)")
    p.add_argument("--loss-chunk", type=int, default=0,
                   help=">0: chunked-vocab cross entropy (no "
                        "[tokens, vocab] logits tensor)")
    p.add_argument("--use-adasum", action="store_true",
                   help="Adasum gradient combination (dp-only layout)")
    p.add_argument("--bf16-allreduce", action="store_true",
                   help="bf16 wire compression for the dp allreduce "
                        "(dp-only layout)")
    p.add_argument("--profile", action="store_true",
                   help="after timing, capture a 3-step XPlane trace and "
                        "print the per-op/per-category breakdown "
                        "(tools/profile_step.py aggregation)")
    args = p.parse_args()
    if args.preset:
        # Preset fills in only what the user left at parser defaults, so
        # e.g. `--preset bert-large --loss-chunk 0` reproduces the dense
        # loss path at preset scale.
        for k, v in PRESETS[args.preset].items():
            if getattr(args, k) == p.get_default(k):
                setattr(args, k, v)
    if args.no_remat:
        args.remat = False

    import jax
    import jax.numpy as jnp
    import optax
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.models import (TransformerConfig, transformer_init,
                                    transformer_logical_axes,
                                    transformer_loss,
                                    transformer_flops_per_token)
    from horovod_tpu.parallel import (make_mesh, logical_to_mesh,
                                      transformer_rules)

    explicit_dp = args.use_adasum or args.bf16_allreduce
    if explicit_dp:
        # Adasum / wire compression need the explicit per-rank gradient
        # path (hvd.DistributedOptimizer inside shard_map over dp); the
        # hybrid tp/pp/sp/ep layout leaves the dp reduction to GSPMD
        # instead, where those options don't apply — fold those axes into
        # dp so the flags work from their defaults.
        folded = args.tp * args.pp * args.sp * args.ep
        if folded > 1:
            print(f"note: --use-adasum/--bf16-allreduce use the dp-only "
                  f"layout; folding tp/pp/sp/ep into dp={args.dp * folded}")
            args.dp *= folded
            args.tp = args.pp = args.sp = args.ep = 1

    hvd.init()
    need = args.dp * args.tp * args.pp * args.sp * args.ep
    devs = jax.devices()
    assert len(devs) >= need, f"need {need} devices, have {len(devs)}"
    mesh = make_mesh(devices=devs[:need], dp=args.dp, tp=args.tp,
                     pp=args.pp, sp=args.sp, ep=args.ep)

    cfg = TransformerConfig(
        vocab=args.vocab, layers=args.layers, d_model=args.d_model,
        heads=args.heads, kv_heads=args.heads, d_ff=args.d_ff,
        max_seq=args.seq, dtype=getattr(jnp, args.dtype),
        num_experts=2 * args.ep if args.ep > 1 else 0,
        sp=args.sp, ep=args.ep, pp=args.pp, remat=args.remat,
        remat_policy=args.remat_policy, loss_chunk=args.loss_chunk)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    rules = transformer_rules()
    axes = transformer_logical_axes(cfg)

    if explicit_dp:
        opt = hvd.DistributedOptimizer(
            optax.adamw(args.lr),
            op=hvd.Adasum if args.use_adasum else hvd.Average,
            compression=(hvd.Compression.bf16 if args.bf16_allreduce
                         else hvd.Compression.none))
    else:
        opt = optax.adamw(args.lr)
    opt_state = opt.init(params)

    # Map stacked-param dims onto manual mesh axes — only axes of size > 1
    # (a size-1 mapping would make params VMA-varying while activations
    # stay invariant, tripping the scan carry type check).
    manual_map = {}
    if args.pp > 1:
        manual_map["stages"] = "pp"
    if args.ep > 1:
        manual_map["experts"] = "ep"

    def manual_spec(tree):
        def keep(lg):
            spec = [manual_map.get(name) for name in lg]
            while spec and spec[-1] is None:
                spec.pop()
            return P(*spec)
        return jax.tree.map(
            keep, tree,
            is_leaf=lambda x: isinstance(x, tuple) and all(
                isinstance(e, (str, type(None))) for e in x))

    def _local_loss(p, t):
        l = transformer_loss(p, t, cfg)
        varying = tuple(set(jax.typeof(l).vma) & {"pp", "sp", "ep"})
        return lax.pmean(l, varying) if varying else l

    # Open the manual island only over axes with degree > 1: a mesh with
    # pp=sp=ep=1 runs the plain loss under GSPMD-auto sharding, where
    # the model's own flash shard_map island (over dp/tp) can engage —
    # nesting it inside a size-1 manual island would force the XLA
    # attention fallback (models/transformer.py _flash_plan).
    manual_axes = {ax for ax, d in (("pp", args.pp), ("sp", args.sp),
                                    ("ep", args.ep)) if d > 1}
    island = (jax.shard_map(
        _local_loss, mesh=mesh,
        in_specs=(manual_spec(axes),
                  P(None, "sp") if args.sp > 1 else P()),
        out_specs=P(), axis_names=manual_axes)
        if manual_axes else
        (lambda p, t: transformer_loss(p, t, cfg)))

    # Single chip uses the plain loss (no shard_map island) so the
    # Pallas flash path can engage; the hybrid layout differentiates
    # through the island.  One step body serves both.
    def make_step(loss_fn):
        def train_step(params, opt_state, tokens):
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss
        return train_step

    # Single chip defaults to the meshless path (no shard_map island,
    # measured ~5% faster back-to-back).  Flash engages under meshes too
    # now — the model opens a partial-manual shard_map island over the
    # GSPMD-auto axes (models/transformer.py _flash_plan) — so
    # HVDT_LM_SINGLE=0/false/off remains only as the A/B knob for
    # meshless-vs-island compilation (example-local, deliberately not in
    # the framework's config registry).
    single = (need == 1 and not explicit_dp
              and os.environ.get("HVDT_LM_SINGLE", "1").lower()
              not in ("0", "false", "off"))

    # Parameter shardings from logical-axis rules (tp/pp/ep placement).
    if not single:
        param_sh = jax.tree.map(
            lambda lg: NamedSharding(mesh, logical_to_mesh(lg, rules, mesh)),
            axes,
            is_leaf=lambda x: isinstance(x, tuple) and all(
                isinstance(e, (str, type(None))) for e in x))
        params = jax.device_put(params, param_sh)
    if explicit_dp:
        def local_step(params, opt_state, tokens):
            def loss_fn(p):
                return transformer_loss(p, tokens, cfg)

            # Differentiate w.r.t. VARYING params so AD keeps per-rank
            # gradients and the optimizer's own fused allreduce (with
            # Adasum combine / wire compression) actually runs — with
            # unvarying params AD inserts a plain psum itself and both
            # options would be silently inert (ref:
            # _DistributedAdasumOptimizer, torch/optimizer.py:345).
            diff = hvd.optimizer.pvary_tree(params, "dp")
            loss, grads = jax.value_and_grad(loss_fn)(diff)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, lax.pmean(loss, "dp")

        # hvd.donated_step = jit + donation + the persistent compilation
        # cache (env-transparent via HVDT_COMPILATION_CACHE).
        step = hvd.donated_step(jax.shard_map(
            local_step, mesh=mesh,
            in_specs=(P(), P(), P("dp")),
            out_specs=(P(), P(), P())), donate_argnums=(0, 1))
    elif single:
        step = hvd.donated_step(
            make_step(lambda p, t: transformer_loss(p, t, cfg)),
            donate_argnums=(0, 1))
    else:
        step = hvd.donated_step(make_step(island), donate_argnums=(0, 1))

    rng = np.random.default_rng(0)
    tok_sharding = (None if single
                    else NamedSharding(mesh, P("dp", "sp")))

    # One fixed synthetic batch (the synthetic-benchmark convention, ref:
    # pytorch_synthetic_benchmark.py): loss decrease is then deterministic
    # (the model overfits it) and the timed loop has no per-step H2D.
    tokens = jax.device_put(
        rng.integers(0, args.vocab, (args.batch, args.seq),
                     dtype=np.int64).astype(np.int32), tok_sharding)

    # Non-single auto-sharded runs execute under the ambient mesh so the
    # model's flash shard_map island sees the auto axes
    # (jax.sharding.get_abstract_mesh in _flash_plan).
    mesh_ctx = (jax.set_mesh(mesh) if not single and not explicit_dp
                else contextlib.nullcontext())
    with mesh_ctx:
        # Warmup/compile
        params, opt_state, loss = step(params, opt_state, tokens)
        first = float(loss)   # host fetch, not block_until_ready: bench.py

        t0 = time.perf_counter()
        for _ in range(args.steps):
            params, opt_state, loss = step(params, opt_state, tokens)
        last = float(loss)
        dt = time.perf_counter() - t0

    tokens_sec = args.steps * args.batch * args.seq / dt
    tflops = (3 * transformer_flops_per_token(cfg) * tokens_sec) / 1e12
    if hvd.rank() == 0:
        print(f"mesh={dict(mesh.shape)}")
        print(f"loss: {first:.4f} -> {last:.4f}")
        print(f"{tokens_sec:.0f} tokens/sec, ~{tflops:.3f} model TFLOP/s")
        assert last < first, "loss should decrease"

    if args.profile:
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), os.pardir, "tools"))
        from profile_step import aggregate, capture, report

        state = [params, opt_state]

        def one():
            p, o, loss = step(state[0], state[1], tokens)
            state[0], state[1] = p, o
            float(loss)   # host fetch keeps device work inside the window

        prof_ctx = (jax.set_mesh(mesh) if not single and not explicit_dp
                    else contextlib.nullcontext())
        # Every rank runs the extra steps (a rank-0-only step() would
        # deadlock multi-process collectives); only rank 0 traces and
        # prints the breakdown.
        with prof_ctx:
            if hvd.rank() == 0:
                path = capture(one, 3)
            else:
                for _ in range(3):
                    one()
        if hvd.rank() == 0:
            print(f"xplane: {path}", file=sys.stderr)
            per_op, per_cat, busy, span = aggregate(path)
            report(per_op, per_cat, busy, span, 3)

    if hvd.rank() == 0:
        print("done.")


if __name__ == "__main__":
    main()
