"""Train-then-serve, end to end: checkpoint an MLP, put it behind HTTP,
hot-reload a better one while traffic flows.

The serving counterpart of examples/jax_mnist.py — it walks the whole
production loop the `horovod_tpu.serve` subsystem exists for:

1. train a small MLP a few steps, `CheckpointManager.save(step, params)`;
2. stand up an in-process `ModelServer` (shape-bucketed engine + dynamic
   batcher) over that checkpoint directory;
3. fire concurrent clients at `/predict` and read `/metrics`;
4. train a few MORE steps, save a newer checkpoint, and watch the server
   hot-swap it (zero dropped requests, zero recompiles).

Runs anywhere, no downloads:
  JAX_PLATFORMS=cpu python examples/jax_serve_mlp.py

For a standalone deployment of an existing checkpoint directory use the
CLI instead:
  python -m horovod_tpu.serve --checkpoint /ckpts --model mlp \
      --mlp-sizes 784,256,128,10 --port 8000
  curl -s localhost:8000/predict -d '{"inputs": [[0.1, ...]]}'
"""

import argparse
import http.client
import json
import tempfile
import threading

import numpy as np


def make_dataset(n, key, num_classes=10, dim=784):
    """Same synthetic class-conditional clusters as jax_mnist.py."""
    centers = np.random.default_rng(1234).normal(
        size=(num_classes, dim)).astype(np.float32)
    rng = np.random.default_rng(key)
    labels = rng.integers(0, num_classes, size=n)
    x = centers[labels] + 0.3 * rng.normal(size=(n, dim)).astype(np.float32)
    return x.astype(np.float32), labels.astype(np.int32)


def post_predict(port, rows):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("POST", "/predict", json.dumps({"inputs": rows}),
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        return r.status, json.loads(r.read())
    finally:
        conn.close()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=60,
                   help="training steps per checkpoint")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--clients", type=int, default=4)
    p.add_argument("--requests-per-client", type=int, default=25)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu.checkpoint import CheckpointManager
    from horovod_tpu.models.mlp import mlp_apply, mlp_init, mlp_loss
    from horovod_tpu.serve import InferenceEngine, ModelServer
    from horovod_tpu.step_pipeline import donated_step

    sizes = (784, 256, 128, 10)
    x_train, y_train = make_dataset(4096, key=0)
    x_test, y_test = make_dataset(512, key=1)

    # ---- 1. train + checkpoint -----------------------------------------
    params = mlp_init(jax.random.PRNGKey(0), sizes)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    def train_step(params, opt_state, xb, yb):
        loss, grads = jax.value_and_grad(mlp_loss)(params, xb, yb)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    step_fn = donated_step(train_step, donate_argnums=(0, 1))

    def train(params, opt_state, steps, start):
        rng = np.random.default_rng(start)
        for i in range(steps):
            idx = rng.integers(0, len(x_train), args.batch_size)
            params, opt_state, loss = step_fn(
                params, opt_state, x_train[idx], y_train[idx])
        return params, opt_state, float(loss)

    ckdir = tempfile.mkdtemp(prefix="hvdt_serve_example_")
    mgr = CheckpointManager(ckdir, max_to_keep=3)
    params, opt_state, loss = train(params, opt_state, args.steps, start=0)
    mgr.save(args.steps, params, force=True)
    print(f"[train] step {args.steps}: loss {loss:.3f} -> checkpoint "
          f"{mgr.step_path(args.steps)}")

    # ---- 2. serve it ----------------------------------------------------
    template = jax.tree.map(jnp.zeros_like, params)
    engine = InferenceEngine(mlp_apply, template, buckets=(1, 8, 32))
    server = ModelServer(engine, port=0, checkpoint_dir=ckdir,
                         template=template, max_delay_ms=3.0)
    port = server.start()
    engine.warmup((sizes[0],))
    print(f"[serve] http://127.0.0.1:{port} — loaded step "
          f"{server.watcher.current_step}, buckets "
          f"{list(engine.buckets)}, {engine.compile_count()} compiles")

    # ---- 3. concurrent traffic -----------------------------------------
    correct, total, failures = [0], [0], [0]
    lock = threading.Lock()

    def client(cid):
        rng = np.random.default_rng(cid)
        for _ in range(args.requests_per_client):
            idx = rng.integers(0, len(x_test), 1 + cid % 4)
            status, body = post_predict(port, x_test[idx].tolist())
            with lock:
                if status != 200:
                    failures[0] += 1
                    continue
                pred = np.argmax(np.asarray(body["outputs"]), axis=-1)
                correct[0] += int((pred == y_test[idx]).sum())
                total[0] += len(idx)

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(args.clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    print(f"[traffic] {total[0]} rows served, {failures[0]} failures, "
          f"accuracy {correct[0] / max(1, total[0]):.2%}, "
          f"compiles still {engine.compile_count()}")

    # ---- 4. hot reload a better model under zero downtime ---------------
    params, opt_state, loss = train(params, opt_state, args.steps,
                                    start=1)
    mgr.save(2 * args.steps, params, force=True)
    reloaded = server.watcher.check_once()
    print(f"[reload] step {reloaded}: loss {loss:.3f}, engine version "
          f"{engine.params_version}, compiles {engine.compile_count()} "
          "(a weight swap never recompiles)")

    status, metrics_text = 0, ""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", "/metrics")
    r = conn.getresponse()
    metrics_text = r.read().decode()
    conn.close()
    for line in metrics_text.splitlines():
        if line.startswith(("serve_request_latency_ms_predict{",
                            "serve_compiles_total",
                            "serve_reloads_total",
                            "serve_batch_fill{")):
            print(f"[metrics] {line}")
    server.stop()


if __name__ == "__main__":
    main()
