"""Train-then-serve for the continuous-batching LLM engine: checkpoint a
tiny transformer LM, decode mixed multi-tenant traffic through the paged
KV cache, and read the engine metrics.

The decode counterpart of examples/jax_serve_mlp.py — it walks the loop
the `horovod_tpu.serve.llm` subsystem exists for:

1. train a tiny `models/transformer.py` LM a few steps on synthetic
   token streams;
2. stand up an in-process `ModelServer` with
   `ContinuousLLMEngine` (paged KV cache + per-iteration scheduler);
3. fire mixed-length prompts from two SLO classes — `interactive` and
   `batch` — at `/predict`, including a duplicate prompt that admission
   serves by copy-on-write prefix sharing;
4. verify the zero-steady-state-recompile contract and the exact KV
   block ledger, then read the `hvdt_engine_*` metrics.

Runs anywhere, no downloads:
  JAX_PLATFORMS=cpu python examples/jax_serve_llm.py

For a standalone deployment of an existing checkpoint directory use the
CLI instead:
  python -m horovod_tpu.serve --checkpoint /ckpts --model transformer \
      --engine continuous --port 8000
  curl -s localhost:8000/predict \
      -d '{"inputs": [[3, 14, 15]], "max_new_tokens": 8}'
"""

import argparse
import http.client
import json
import threading


def post_predict(port, doc):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    try:
        conn.request("POST", "/predict", json.dumps(doc),
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        return r.status, json.loads(r.read())
    finally:
        conn.close()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=30, help="training steps")
    p.add_argument("--requests", type=int, default=8)
    p.add_argument("--new-tokens", type=int, default=12)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from horovod_tpu.models.transformer import (TransformerConfig,
                                                transformer_init,
                                                transformer_loss)
    from horovod_tpu.serve import ModelServer
    from horovod_tpu.serve.llm import ContinuousLLMEngine
    from horovod_tpu.step_pipeline import donated_step

    cfg = TransformerConfig(vocab=256, layers=2, d_model=64, heads=4,
                            kv_heads=4, d_ff=128, max_seq=128,
                            dtype=jnp.float32)

    # ---- 1. train a few steps on a synthetic token stream ---------------
    rng = np.random.default_rng(0)
    stream = rng.integers(1, cfg.vocab, size=20000).astype(np.int32)

    params = transformer_init(jax.random.PRNGKey(0), cfg)
    opt = optax.adam(3e-4)
    opt_state = opt.init(params)

    def train_step(params, opt_state, xb):
        loss, grads = jax.value_and_grad(transformer_loss)(params, xb, cfg)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    step_fn = donated_step(train_step, donate_argnums=(0, 1))
    for i in range(args.steps):
        idx = rng.integers(0, len(stream) - 65, size=8)
        xb = np.stack([stream[j:j + 64] for j in idx])
        params, opt_state, loss = step_fn(params, opt_state, xb)
    print(f"[train] step {args.steps}: loss {float(loss):.3f}")

    # ---- 2. serve it through the continuous engine ----------------------
    engine = ContinuousLLMEngine(params, cfg, decode_slots=4,
                                 num_blocks=64, block_size=8,
                                 seq_blocks=16, prefill_chunk=32)
    server = ModelServer(engine, port=0)
    port = server.start()
    engine.warmup()
    baseline_compiles = engine.compile_count()
    print(f"[serve] http://127.0.0.1:{port} — engine=continuous, "
          f"max_context={engine.max_context}, "
          f"{baseline_compiles} warmup compiles")

    # ---- 3. mixed multi-tenant traffic ----------------------------------
    prompts = [[int(t) for t in rng.integers(1, cfg.vocab, size=n)]
               for n in rng.integers(3, 40, size=args.requests)]
    prompts.append(list(prompts[0]))            # duplicate -> prefix fork
    results = [None] * len(prompts)
    failures = [0]
    lock = threading.Lock()

    def client(i, max_new):
        tenant = "interactive" if i % 2 == 0 else "batch"
        status, body = post_predict(port, {
            "inputs": [prompts[i]],
            "max_new_tokens": max_new,
            "tenant": tenant,
        })
        with lock:
            if status != 200:
                failures[0] += 1
            else:
                results[i] = body["outputs"][0]

    # The duplicate forks the parent's block table only while the parent
    # is LIVE and fully prefilled — give the parent a long generation,
    # let it reach decode, then fire everyone else (duplicate included).
    parent = threading.Thread(target=client,
                              args=(0, 8 * args.new_tokens))
    parent.start()
    import time as _time
    for _ in range(200):
        with engine._lock:
            live = [s for s in engine.sched.admitted if s.decode_ready]
        if live:
            break
        _time.sleep(0.01)
    threads = [threading.Thread(target=client, args=(i, args.new_tokens))
               for i in range(1, len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    parent.join()

    done = sum(r is not None for r in results)
    print(f"[traffic] {done}/{len(prompts)} prompts decoded, "
          f"{failures[0]} failures, "
          f"sample: {results[0][:6] if results[0] else None}...")
    if results[0] is not None and results[-1] is not None:
        shared = ("identical"
                  if results[0][:len(results[-1])] == results[-1]
                  else "DIVERGED")
        print(f"[prefix] duplicate prompt decode: {shared}, "
              f"prefix hits {engine.sched.prefix_hits}, "
              f"CoW copies {engine.alloc.cow_copies}")

    # ---- 4. contracts: zero recompiles, exact block ledger --------------
    engine.alloc.check()
    print(f"[ledger] blocks allocated {engine.alloc.blocks_allocated} == "
          f"freed {engine.alloc.blocks_freed}, in use "
          f"{engine.alloc.used_blocks}")
    print(f"[compiles] {engine.compile_count()} total "
          f"(delta {engine.compile_count() - baseline_compiles} — "
          "steady-state traffic never recompiles)")

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", "/metrics")
    metrics_text = conn.getresponse().read().decode()
    conn.close()
    for line in metrics_text.splitlines():
        if line.startswith(("hvdt_engine_tokens_per_sec",
                            "hvdt_engine_decode_tokens_total",
                            "hvdt_engine_preemptions_total",
                            "hvdt_engine_prefix_hits_total",
                            "hvdt_engine_kv_blocks_in_use")):
            print(f"[metrics] {line}")
    server.stop()


if __name__ == "__main__":
    main()
