"""Elastic training: survive scale-up/down and preemption mid-epoch.

Re-conception of ref: examples/elastic/pytorch/pytorch_mnist_elastic.py —
the State/commit/restore pattern (SURVEY.md §3.4): wrap training in
``hvd.elastic.run``; commit state at intervals; on membership change the
loop re-rendezvouses, re-broadcasts state, and the ElasticSampler
repartitions the *remaining* samples of the epoch over the new world.

Launch under the elastic driver:
    hvdtrun --elastic --host-discovery-script ./discover.sh \
        python examples/jax_mnist_elastic.py
(also runs standalone single-process for a smoke test).
"""

import argparse

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--batches-per-commit", type=int, default=10)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.data import ElasticSampler
    from horovod_tpu.models import mlp_init, mlp_loss

    hvd.init()

    # Synthetic learnable data (see jax_mnist.py).
    centers = np.random.default_rng(1234).normal(size=(10, 784)).astype(
        np.float32)
    rng = np.random.default_rng(0)
    labels_all = rng.integers(0, 10, size=4096).astype(np.int32)
    x_all = (centers[labels_all]
             + 0.3 * rng.normal(size=(4096, 784))).astype(np.float32)

    params = mlp_init(jax.random.PRNGKey(0))
    opt = hvd.DistributedOptimizer(optax.sgd(args.lr * hvd.size(),
                                             momentum=0.9))
    opt_state = opt.init(params)
    sampler = ElasticSampler(len(x_all), shuffle=True, seed=0)

    # Everything that must survive a re-rendezvous lives on the state.
    state = hvd.elastic.JaxState(params=params, opt_state=opt_state,
                                 sampler=sampler, epoch=0, batch_idx=0)

    def make_step():
        mesh = hvd.mesh()

        def local_step(params, opt_state, x, y):
            loss, grads = jax.value_and_grad(
                lambda pp: mlp_loss(pp, x, y))(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state,
                    jax.lax.pmean(loss, "dp"))

        step = jax.jit(jax.shard_map(
            local_step, mesh=mesh,
            in_specs=(P(), P(), P("dp"), P("dp")),
            out_specs=(P(), P(), P())))
        return mesh, step

    @hvd.elastic.run
    def train(state):
        # (Re)build mesh + step for the current topology: after a reset
        # the device set changed, so compiled programs must be rebuilt.
        mesh, step = make_step()
        batch_sharding = NamedSharding(mesh, P("dp"))
        per_proc = args.batch_size  # per-process batch rows
        while state.epoch < args.epochs:
            state.sampler.reset()
            idx = np.fromiter(state.sampler, np.int64)
            steps_total = len(idx) // per_proc
            for b in range(state.batch_idx, steps_total):
                sel = idx[b * per_proc:(b + 1) * per_proc]
                xb = jax.device_put(x_all[sel], batch_sharding)
                yb = jax.device_put(labels_all[sel], batch_sharding)
                state.params, state.opt_state, loss = step(
                    state.params, state.opt_state, xb, yb)
                state.sampler.record_batch(b, per_proc)
                state.batch_idx = b + 1
                if (b + 1) % args.batches_per_commit == 0:
                    # Snapshot + host-update check; raises
                    # HostsUpdatedInterrupt on membership change.
                    state.commit()
            if hvd.rank() == 0:
                print(f"epoch {state.epoch}: loss={float(loss):.4f} "
                      f"world={hvd.size()}")
            state.epoch += 1
            state.batch_idx = 0
            state.sampler.set_epoch(state.epoch)
            state.commit()

    train(state)
    if hvd.rank() == 0:
        print("elastic run complete.")


if __name__ == "__main__":
    main()
