"""The reference's canonical PyTorch MNIST script, ported line-for-line.

This is the porting-guide (docs/porting.md) proof artifact: the training
loop, model, optimizer wrapping, sampler, and metric averaging follow
ref: examples/pytorch/pytorch_mnist.py — the only substantive changes:

* ``import horovod.torch as hvd`` -> ``import horovod_tpu as hvd`` with
  the torch binding pulled from ``horovod_tpu.interop.torch``;
* torchvision's downloaded MNIST -> synthetic MNIST-shaped data (this
  image has no dataset egress); same shapes, same sampler flow;
* ``hvd.Compression.fp16`` -> kept (works), bf16 also available.

Everything else — DistributedSampler rank/size wiring, Adasum LR
scaling, gradient predivide, per-epoch test-metric averaging — is the
reference's own structure running on this framework's eager controller.

Run: python examples/torch_mnist_ported.py --epochs 2
     (or under the launcher: hvdtrun -np 2 python examples/torch_mnist_ported.py)
"""

import argparse
import os

# Torch does the compute; JAX is only the communication runtime here, so
# pin it to CPU regardless of what the outer environment points JAX at —
# both the env var (pre-registration) and the config (a sitecustomize may
# have force-registered an accelerator platform at interpreter start).
os.environ["JAX_PLATFORMS"] = "cpu"
import jax

jax.config.update("jax_platforms", "cpu")

import torch
import torch.nn as nn
import torch.nn.functional as F
import torch.optim as optim
import torch.utils.data.distributed

import horovod_tpu as hvd
from horovod_tpu.interop.torch import DistributedOptimizer

parser = argparse.ArgumentParser(description="PyTorch MNIST (ported)")
parser.add_argument("--batch-size", type=int, default=64)
parser.add_argument("--test-batch-size", type=int, default=1000)
parser.add_argument("--epochs", type=int, default=2)
parser.add_argument("--lr", type=float, default=0.01)
parser.add_argument("--momentum", type=float, default=0.5)
parser.add_argument("--seed", type=int, default=42)
parser.add_argument("--log-interval", type=int, default=10)
parser.add_argument("--fp16-allreduce", action="store_true")
parser.add_argument("--use-adasum", action="store_true")
parser.add_argument("--gradient-predivide-factor", type=float, default=1.0)
parser.add_argument("--train-size", type=int, default=2048,
                    help="synthetic dataset size (stand-in for MNIST)")


class Net(nn.Module):
    # ref: pytorch_mnist.py Net — identical
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(1, 10, kernel_size=5)
        self.conv2 = nn.Conv2d(10, 20, kernel_size=5)
        self.conv2_drop = nn.Dropout2d()
        self.fc1 = nn.Linear(320, 50)
        self.fc2 = nn.Linear(50, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.conv2_drop(self.conv2(x)), 2))
        x = x.view(-1, 320)
        x = F.relu(self.fc1(x))
        x = F.dropout(x, training=self.training)
        x = self.fc2(x)
        return F.log_softmax(x, dim=-1)


def synthetic_mnist(n, seed):
    """MNIST-shaped learnable synthetic data: label = brightest quadrant
    pair (classes separable, so accuracy demonstrably rises)."""
    g = torch.Generator().manual_seed(seed)
    x = torch.rand(n, 1, 28, 28, generator=g)
    q = torch.stack([x[:, 0, :14, :14].mean((1, 2)),
                     x[:, 0, :14, 14:].mean((1, 2)),
                     x[:, 0, 14:, :14].mean((1, 2)),
                     x[:, 0, 14:, 14:].mean((1, 2))], 1)
    y = q.argmax(1) + 2 * (x[:, 0].mean((1, 2)) > 0.5).long()
    for c in range(10):
        idx = y == c
        x[idx, 0, :3, :3] = c / 10.0          # a learnable corner cue
    return torch.utils.data.TensorDataset(x, y)


def metric_average(val, name):
    # ref: pytorch_mnist.py metric_average — identical call shape
    import numpy as np

    return float(hvd.allreduce(np.float32(val), name=name))


def train(epoch, model, optimizer, loader, sampler, args):
    model.train()
    sampler.set_epoch(epoch)
    for batch_idx, (data, target) in enumerate(loader):
        optimizer.zero_grad()
        output = model(data)
        loss = F.nll_loss(output, target)
        loss.backward()
        optimizer.step()
        if batch_idx % args.log_interval == 0 and hvd.rank() == 0:
            print(f"Train Epoch: {epoch} [{batch_idx * len(data)}/"
                  f"{len(sampler)}]\tLoss: {loss.item():.6f}")


def test(model, loader, args):
    model.eval()
    test_loss, test_accuracy, n = 0.0, 0.0, 0
    with torch.no_grad():
        for data, target in loader:
            output = model(data)
            test_loss += F.nll_loss(output, target, reduction="sum").item()
            pred = output.argmax(1)
            test_accuracy += pred.eq(target).sum().item()
            n += len(data)
    test_loss = metric_average(test_loss / n, "avg_loss")
    test_accuracy = metric_average(test_accuracy / n, "avg_accuracy")
    if hvd.rank() == 0:
        print(f"Test set: Average loss: {test_loss:.4f}, "
              f"Accuracy: {100.0 * test_accuracy:.2f}%")
    return test_loss


def main():
    args = parser.parse_args()
    hvd.init()
    torch.manual_seed(args.seed)
    torch.set_num_threads(1)

    train_dataset = synthetic_mnist(args.train_size, args.seed)
    # ref: torch.utils.data.distributed.DistributedSampler wired with
    # hvd.size()/hvd.rank() — identical
    train_sampler = torch.utils.data.distributed.DistributedSampler(
        train_dataset, num_replicas=hvd.size(), rank=hvd.rank())
    train_loader = torch.utils.data.DataLoader(
        train_dataset, batch_size=args.batch_size, sampler=train_sampler)

    test_dataset = synthetic_mnist(args.test_batch_size, args.seed + 1)
    test_sampler = torch.utils.data.distributed.DistributedSampler(
        test_dataset, num_replicas=hvd.size(), rank=hvd.rank())
    test_loader = torch.utils.data.DataLoader(
        test_dataset, batch_size=args.test_batch_size, sampler=test_sampler)

    model = Net()
    # ref: Adasum needs no LR scaling; otherwise scale by world size
    lr_scaler = 1 if args.use_adasum else hvd.size()
    optimizer = optim.SGD(model.parameters(), lr=args.lr * lr_scaler,
                          momentum=args.momentum)

    # ref: broadcast parameters & optimizer state from rank 0
    state = {k: v.detach().numpy() for k, v in model.state_dict().items()}
    state = hvd.broadcast_parameters(state, root_rank=0)
    model.load_state_dict({k: torch.from_numpy(v.copy())
                           for k, v in state.items()})

    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    optimizer = DistributedOptimizer(
        optimizer,
        named_parameters=model.named_parameters(),
        compression=compression,
        op=hvd.Adasum if args.use_adasum else hvd.Average,
        gradient_predivide_factor=args.gradient_predivide_factor)

    loss0 = None
    for epoch in range(1, args.epochs + 1):
        train(epoch, model, optimizer, train_loader, train_sampler, args)
        loss = test(model, test_loader, args)
        loss0 = loss0 if loss0 is not None else loss
    assert loss <= loss0, "test loss should not regress"
    hvd.shutdown()


if __name__ == "__main__":
    main()
