"""Synthetic throughput benchmark — images/sec with stddev.

Re-conception of ref: examples/pytorch/pytorch_synthetic_benchmark.py
(same CLI: --model/--batch-size/--num-iters/--num-batches-per-iter/
--num-warmup-batches/--use-adasum/--fp16-allreduce; same output shape:
per-iter img/sec lines, then totals).  TPU-native: bf16 compute, NHWC,
jitted train step with donated buffers, optional dp sharding over all
local devices via shard_map.

Single chip (or CPU sim):
    python examples/jax_synthetic_benchmark.py --num-iters 3

Scaling efficiency (the reference's headline metric — ref:
docs/benchmarks.rst:8-43, the 90%/68% @512-GPU table):
    python examples/jax_synthetic_benchmark.py --scaling-efficiency
measures rate(1) on one device and rate(n) dp-sharded over the whole
mesh, reporting ``rate(n) / (n * rate(1))``.
"""

import argparse
import time

import numpy as np


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50",
                   choices=["resnet50", "resnet101", "vgg16", "mlp",
                            "transformer"])
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-device batch size")
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-warmup-batches", type=int, default=10)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--use-adasum", action="store_true")
    p.add_argument("--fp16-allreduce", action="store_true")
    p.add_argument("--no-shard", action="store_true",
                   help="single-device step (no dp axis)")
    p.add_argument("--scaling-efficiency", action="store_true",
                   help="measure rate(n)/(n*rate(1)) over the dp mesh")
    p.add_argument("--autotune", action="store_true",
                   help="drive the fusion-knob autotuner from measured "
                        "step rates (ref: HOROVOD_AUTOTUNE)")
    p.add_argument("--fused-optimizer", action="store_true",
                   help="run the update through the fused Pallas "
                        "optimizer kernels (hvd.fused_sgd) — one HBM "
                        "pass per eligible parameter; also the starting "
                        "point for the autotuner's fused dimension "
                        "(HVDT_AUTOTUNE_FUSED_OPTIMIZER=1)")
    return p.parse_args(argv)


def measure(args, use_shard: bool, quiet: bool = False) -> float:
    """One full benchmark run; returns mean images(samples)/sec total."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd

    mesh = hvd.mesh()
    n_dev = mesh.devices.size if use_shard else 1
    global_batch = args.batch_size * n_dev

    key = jax.random.PRNGKey(0)
    if args.model in ("resnet50", "resnet101"):
        from horovod_tpu.models import (ResNetConfig, resnet50_init,
                                        resnet_loss)

        cfg = ResNetConfig(num_classes=1000, dtype=jnp.bfloat16,
                           depth=int(args.model[6:]))
        params, stats = resnet50_init(key, cfg)
        data = jax.random.normal(
            key, (global_batch, args.image_size, args.image_size, 3),
            jnp.bfloat16)
        labels = jnp.zeros((global_batch,), jnp.int32)

        def loss_fn(p, xb, yb):
            loss, _ = resnet_loss(p, stats, xb, yb, cfg)
            return loss
    elif args.model == "vgg16":
        from horovod_tpu.models import VGGConfig, vgg16_init, vgg_loss

        cfg = VGGConfig(num_classes=1000, dtype=jnp.bfloat16,
                        image_size=args.image_size)
        params = vgg16_init(key, cfg)
        data = jax.random.normal(
            key, (global_batch, args.image_size, args.image_size, 3),
            jnp.bfloat16)
        labels = jnp.zeros((global_batch,), jnp.int32)

        def loss_fn(p, xb, yb):
            return vgg_loss(p, xb, yb, cfg)
    elif args.model == "transformer":
        from horovod_tpu.models import (TransformerConfig, transformer_init,
                                        transformer_loss)

        cfg = TransformerConfig(vocab=32000, layers=12, d_model=768,
                                heads=12, kv_heads=12, d_ff=3072,
                                max_seq=512, dtype=jnp.bfloat16)
        params = transformer_init(key, cfg)
        data = jax.random.randint(key, (global_batch, 512), 0, 32000)
        labels = None

        def loss_fn(p, xb, yb):
            return transformer_loss(p, xb, cfg)
    else:
        from horovod_tpu.models import mlp_init, mlp_loss

        params = mlp_init(key)
        data = jax.random.normal(key, (global_batch, 784))
        labels = jnp.zeros((global_batch,), jnp.int32)
        loss_fn = mlp_loss

    def build_step(threshold_bytes=None, fused=None):
        """(Re-)jit the train step for a fusion-bucket threshold — the
        autotuner's 'apply' operation (thresholds are trace-time
        constants under XLA).  ``fused`` picks the update lowering
        (fused Pallas kernels vs stock optax) — the autotuner's second
        A/B dimension."""
        from horovod_tpu.step_pipeline import donated_step

        if fused is not None:
            # Autotuner-driven A/B: both legs use the fused
            # transformation (use_kernels flips the lowering) so the
            # opt-state structure survives mid-run knob changes.
            inner = hvd.fused_sgd(0.01, momentum=0.9,
                                  use_kernels=bool(fused))
        elif args.fused_optimizer:
            inner = hvd.fused_sgd(0.01, momentum=0.9)
        else:
            inner = optax.sgd(0.01, momentum=0.9)
        opt = hvd.DistributedOptimizer(
            inner,
            op=hvd.Adasum if args.use_adasum else hvd.Average,
            compression=(hvd.Compression.bf16 if args.fp16_allreduce
                         else hvd.Compression.none),
            threshold_bytes=threshold_bytes)

        def local_step(params, opt_state, xb, yb):
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(p, xb, yb))(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            if use_shard:
                loss = jax.lax.pmean(loss, "dp")
            return optax.apply_updates(params, updates), opt_state, loss

        # donated_step = jit + params/opt-state donation + the persistent
        # compilation cache (env-transparent, HVDT_COMPILATION_CACHE).
        if not use_shard:
            return opt, donated_step(local_step, donate_argnums=(0, 1))
        return opt, donated_step(jax.shard_map(
            local_step, mesh=mesh,
            in_specs=(P(), P(), P("dp"), P() if labels is None else P("dp")),
            out_specs=(P(), P(), P())),
            donate_argnums=(0, 1))

    from horovod_tpu.autotune import autotuned_step

    # Env-transparent autotune: `hvdtrun --autotune` exports
    # HVDT_AUTOTUNE=1 and the wrapper engages by itself (zero-overhead
    # passthrough otherwise); --autotune here just forces it on.  The
    # builder records the optimizer each (re-)build so opt always is the
    # instance the live step closes over.
    built = {}

    def builder(tb, fused=None):
        built["opt"], step_fn = build_step(tb, fused)
        return step_fn

    step = autotuned_step(builder, tree_example=params,
                          enabled=(True if args.autotune and use_shard
                                   else None if use_shard else False),
                          steps_per_sample=args.num_batches_per_iter)
    opt = built["opt"]
    opt_state = opt.init(params)
    if use_shard:
        data = jax.device_put(data, NamedSharding(mesh, P("dp")))
        if labels is not None:
            labels = jax.device_put(labels, NamedSharding(mesh, P("dp")))

    dev = jax.devices()[0]
    verbose = hvd.rank() == 0 and not quiet
    if verbose:
        print(f"Model: {args.model}")
        print(f"Batch size: {global_batch} ({args.batch_size}/device, "
              f"{n_dev} devices)")
        print(f"Device: {dev.platform}:{dev.device_kind}")

    def run_batches(n):
        nonlocal params, opt_state
        for _ in range(n):
            params, opt_state, loss = step(params, opt_state, data, labels)
        # Host fetch (not block_until_ready — a no-op on some tunnelled
        # PJRT backends) so the timed region covers real device work.
        float(jnp.sum(loss))

    run_batches(args.num_warmup_batches)

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        run_batches(args.num_batches_per_iter)
        dt = time.perf_counter() - t0
        rate = global_batch * args.num_batches_per_iter / dt
        if verbose:
            print(f"Iter #{i}: {rate:.1f} img/sec total")
            if step.enabled and step.bucket_bytes:
                print(f"  autotune bucket {step.bucket_bytes // 2**20} MiB")
        img_secs.append(rate)

    if verbose:
        mean, std = np.mean(img_secs), np.std(img_secs)
        print(f"Img/sec total: {mean:.1f} +- {1.96 * std:.1f}")
        print(f"Img/sec/device: {mean / n_dev:.1f}")
        if step.enabled:
            print(f"Autotune: {step.summary()}")
    return float(np.mean(img_secs))


def main():
    args = parse_args()

    import horovod_tpu as hvd

    hvd.init()
    if not args.scaling_efficiency:
        measure(args, use_shard=not args.no_shard)
        return

    n = hvd.mesh().devices.size
    rate1 = measure(args, use_shard=False, quiet=True)
    raten = measure(args, use_shard=True, quiet=True)
    eff = raten / (n * rate1) if n and rate1 else 0.0
    if hvd.rank() == 0:
        print(f"rate(1)     : {rate1:.1f} samples/sec")
        print(f"rate({n})    : {raten:.1f} samples/sec "
              f"({raten / n:.1f}/device)")
        print(f"scaling efficiency rate({n})/({n}*rate(1)) = {eff:.3f}")
        import json

        print(json.dumps({"metric": "scaling_efficiency",
                          "value": round(eff, 4), "n_devices": n,
                          "model": args.model,
                          "rate1": round(rate1, 2),
                          "raten": round(raten, 2)}))


if __name__ == "__main__":
    main()
