"""MNIST-style training with the DistributedOptimizer — the canonical demo.

Re-conception of ref: examples/pytorch/pytorch_mnist.py — same program
shape (init → shard data per rank → wrap optimizer → broadcast initial
state → train with metric averaging → rank-0 reporting), re-designed for
TPU: one *process* drives all local devices; the per-device batch split
happens in the jitted step via shard_map over the 'dp' mesh axis, not via
one process per accelerator.

Runs anywhere: real TPU, or CPU simulation with
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/jax_mnist.py --epochs 2

Data is synthetic (deterministic class-conditional clusters) so the
example has zero downloads; swap `make_dataset` for a real loader.
"""

import argparse

import numpy as np


def make_dataset(n, key, num_classes=10, dim=784):
    """Class-conditional Gaussian clusters — learnable stand-in for MNIST.
    Cluster centers are fixed (seed 1234) so train/test share the task."""
    centers = np.random.default_rng(1234).normal(
        size=(num_classes, dim)).astype(np.float32)
    rng = np.random.default_rng(key)
    labels = rng.integers(0, num_classes, size=n)
    x = centers[labels] + 0.3 * rng.normal(size=(n, dim)).astype(np.float32)
    return x.astype(np.float32), labels.astype(np.int32)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=64,
                   help="per-device batch size")
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--use-adasum", action="store_true",
                   help="use Adasum reduction instead of averaging")
    p.add_argument("--fp16-allreduce", action="store_true",
                   help="compress gradients to bf16 on the wire")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.data import DistributedSampler, prefetch_to_device
    from horovod_tpu.models import mlp_init, mlp_apply, mlp_loss

    hvd.init()
    mesh = hvd.mesh()
    n_dev = mesh.devices.size
    global_batch = args.batch_size * n_dev

    # Scale LR by world size like the reference example
    # (ref: pytorch_mnist.py lr_scaler; Adasum needs no scaling).
    lr = args.lr * (1 if args.use_adasum else n_dev)

    params = mlp_init(jax.random.PRNGKey(42))
    opt = hvd.DistributedOptimizer(
        optax.sgd(lr, momentum=0.9),
        op=hvd.Adasum if args.use_adasum else hvd.Average,
        compression=(hvd.Compression.bf16 if args.fp16_allreduce
                     else hvd.Compression.none))
    opt_state = opt.init(params)

    # Broadcast initial state from rank 0 (multi-process determinism;
    # ref: broadcast_parameters + broadcast_optimizer_state).
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt_state = hvd.broadcast_optimizer_state(opt_state, root_rank=0)

    def local_step(params, opt_state, x, y):
        def loss_fn(p):
            return mlp_loss(p, x, y)

        # For Adasum, differentiate w.r.t. *varying* params so AD keeps
        # per-rank gradients (otherwise it inserts the psum itself and
        # there is nothing left to combine scale-invariantly).
        diff_params = (hvd.optimizer.pvary_tree(params, "dp")
                       if args.use_adasum else params)
        loss, grads = jax.value_and_grad(loss_fn)(diff_params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        acc = jnp.mean(
            (jnp.argmax(mlp_apply(params, x), -1) == y).astype(jnp.float32))
        return params, opt_state, jax.lax.pmean(loss, "dp"), \
            jax.lax.pmean(acc, "dp")

    # Donate the carried (params, opt_state) so XLA updates them in
    # place instead of double-buffering every step (hvd.donated_step
    # also engages the persistent compile cache when configured).
    step = hvd.donated_step(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), P(), P("dp"), P("dp")),
        out_specs=(P(), P(), P(), P())),
        donate_argnums=(0, 1))

    x_train, y_train = make_dataset(8192, key=0)
    x_test, y_test = make_dataset(1024, key=1)
    batch_sharding = NamedSharding(mesh, P("dp"))

    # This process's shard of each global batch (one process here; under
    # hvdtrun each process loads only its slice).
    sampler = DistributedSampler(len(x_train), shuffle=True, seed=0)

    test_fwd = jax.jit(mlp_apply)

    for epoch in range(args.epochs):
        sampler.set_epoch(epoch)
        idx = np.fromiter(sampler, dtype=np.int64)
        steps = len(idx) // global_batch

        def batches():
            for s in range(steps):
                sel = idx[s * global_batch:(s + 1) * global_batch]
                yield x_train[sel], y_train[sel]

        last = None
        for xb, yb in prefetch_to_device(batches(), size=2,
                                         sharding=batch_sharding):
            params, opt_state, loss, acc = step(params, opt_state, xb, yb)
            last = (loss, acc)
        train_loss, train_acc = float(last[0]), float(last[1])

        # Eager metric averaging across processes
        # (ref: pytorch_mnist.py metric_average via hvd.allreduce).
        logits = test_fwd(params, jnp.asarray(x_test))
        test_acc = float(jnp.mean((jnp.argmax(logits, -1)
                                   == jnp.asarray(y_test)).astype(jnp.float32)))
        test_acc = float(np.asarray(hvd.allreduce(
            np.float32(test_acc), name="test_acc")))
        if hvd.rank() == 0:
            print(f"epoch {epoch}: train_loss={train_loss:.4f} "
                  f"train_acc={train_acc:.4f} test_acc={test_acc:.4f}")

    if hvd.rank() == 0:
        assert test_acc > 0.9, "did not learn — check setup"
        print("done.")


if __name__ == "__main__":
    main()
