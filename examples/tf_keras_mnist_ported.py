"""The reference's canonical TF2 Keras MNIST script, ported line-for-line.

Porting-guide (docs/porting.md) proof artifact for the TF/Keras surface:
model, optimizer wrapping, callback stack, LR warmup, rank-0-only
checkpointing and verbosity follow
ref: examples/tensorflow2/tensorflow2_keras_mnist.py — the only
substantive changes:

* ``import horovod.tensorflow.keras as hvd`` ->
  ``import horovod_tpu.interop.tf as hvd`` (the interop module re-exports
  the core API, so ``hvd.init()``/``hvd.size()``/callbacks all resolve);
* the GPU-pinning block -> pinning JAX (the communication runtime) to
  CPU: TF does the compute here, there are no GPUs to pin;
* downloaded MNIST -> synthetic MNIST-shaped data (no dataset egress);
* ``backward_passes_per_step``/``average_aggregated_gradients`` knobs
  -> dropped (local aggregation is a JAX-path feature; the wrapped
  optimizer averages every step, the reference's default).

Run: python examples/tf_keras_mnist_ported.py --epochs 2
     (or: hvdtrun -np 2 python examples/tf_keras_mnist_ported.py)
"""

import argparse
import os

# TF does the compute; JAX is only the communication runtime here.
os.environ["JAX_PLATFORMS"] = "cpu"
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import tensorflow as tf

import horovod_tpu.interop.tf as hvd

parser = argparse.ArgumentParser(description="TF2 Keras MNIST (ported)")
parser.add_argument("--epochs", type=int, default=2)
parser.add_argument("--batch-size", type=int, default=128)
parser.add_argument("--steps-per-epoch", type=int, default=None,
                    help="default: 500 // size, like the reference")
parser.add_argument("--warmup-epochs", type=int, default=3)
parser.add_argument("--samples", type=int, default=4096,
                    help="synthetic dataset size per rank")
args = parser.parse_args()

# Horovod: initialize Horovod.
hvd.init()

# Synthetic MNIST-shaped data, seeded per rank like the reference's
# per-rank download path ('mnist-%d.npz' % hvd.rank()).
rng = np.random.RandomState(hvd.rank())
mnist_images = rng.randint(0, 256, (args.samples, 28, 28)).astype(np.uint8)
mnist_labels = rng.randint(0, 10, (args.samples,)).astype(np.int64)

dataset = tf.data.Dataset.from_tensor_slices(
    (tf.cast(mnist_images[..., tf.newaxis] / 255.0, tf.float32),
     tf.cast(mnist_labels, tf.int64))
)
dataset = dataset.repeat().shuffle(10000).batch(args.batch_size)

mnist_model = tf.keras.Sequential([
    tf.keras.layers.Input((28, 28, 1)),
    tf.keras.layers.Conv2D(32, [3, 3], activation="relu"),
    tf.keras.layers.Conv2D(64, [3, 3], activation="relu"),
    tf.keras.layers.MaxPooling2D(pool_size=(2, 2)),
    tf.keras.layers.Dropout(0.25),
    tf.keras.layers.Flatten(),
    tf.keras.layers.Dense(128, activation="relu"),
    tf.keras.layers.Dropout(0.5),
    tf.keras.layers.Dense(10, activation="softmax"),
])

# Horovod: adjust learning rate based on number of workers.
scaled_lr = 0.001 * hvd.size()
opt = tf.keras.optimizers.Adam(scaled_lr)

# Horovod: add Horovod DistributedOptimizer.
opt = hvd.DistributedOptimizer(opt)

mnist_model.compile(
    loss=tf.keras.losses.SparseCategoricalCrossentropy(),
    optimizer=opt,
    metrics=["accuracy"])

callbacks = [
    # Horovod: broadcast initial variable states from rank 0 to all other
    # processes (consistent init / restored checkpoints).
    hvd.BroadcastGlobalVariablesCallback(0),

    # Horovod: average metrics among workers at the end of every epoch.
    hvd.MetricAverageCallback(),

    # Horovod: scale the LR in over the first epochs (arXiv:1706.02677).
    hvd.LearningRateWarmupCallback(initial_lr=scaled_lr,
                                   warmup_epochs=args.warmup_epochs,
                                   verbose=1),
]

# Horovod: save checkpoints only on worker 0.
if hvd.rank() == 0:
    callbacks.append(tf.keras.callbacks.ModelCheckpoint(
        "./checkpoint-{epoch}.keras"))

# Horovod: write logs on worker 0.
verbose = 1 if hvd.rank() == 0 else 0

# Train; Horovod: adjust number of steps based on number of workers.
steps = args.steps_per_epoch or max(1, 500 // hvd.size())
mnist_model.fit(dataset, steps_per_epoch=steps, callbacks=callbacks,
                epochs=args.epochs, verbose=verbose)
hvd.shutdown()

# TF and JAX each embed a full C++ runtime; letting interpreter
# finalization tear both down intermittently aborts in a C++ destructor
# (a thread hits forced unwind mid-exception — observed ~2/10 runs,
# AFTER all work and shutdown() completed).  hvd.shutdown() has already
# barriered the job and closed the collective runtime, so exit hard.
# JAX-only workers don't need this (docs/porting.md "TF interop notes").
os._exit(0)
