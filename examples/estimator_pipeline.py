"""Spark-ML-style estimator workflow, end to end, no Spark required.

Ref analog: the reference's Spark estimator examples
(examples/spark/keras/keras_spark_rossmann_estimator.py shape: build an
estimator with params, fit a DataFrame, transform, save) — here on the
framework's own orchestration: a declarative ``JaxEstimator`` trains
data-parallel over local worker processes, the Params surface drives
config, the model handle persists and reloads, and the native
``Pipeline`` chains stages.  With pyspark installed, the SAME estimator
drops into ``pyspark.ml.Pipeline`` after
``orchestrate.register_pyspark_stages()``.

Run:  python examples/estimator_pipeline.py [--workers 2]
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--epochs", type=int, default=6)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu.orchestrate import JaxEstimator, JaxModel, load_ml

    rng = np.random.default_rng(0)
    w_true = np.array([2.0, -1.0, 0.5], np.float32)
    X = rng.normal(size=(512, 3)).astype(np.float32)
    y = X @ w_true + 0.01 * rng.normal(size=512).astype(np.float32)

    est = JaxEstimator(
        model_init=lambda key: {"w": jnp.zeros(3, jnp.float32)},
        loss_fn=lambda p, xb, yb: jnp.mean((xb @ p["w"] - yb) ** 2),
        predict_fn=lambda p, x: np.asarray(x) @ np.asarray(p["w"]),
        optimizer=optax.sgd(0.3),
        num_workers=args.workers,
        validation_split=0.25,
        batch_size=32)

    # Params surface (ref: EstimatorParams setters) — chainable,
    # re-validated by the constructor on every set.
    est.setEpochs(args.epochs).setParams(seed=7)
    print("params:", est.explainParams().replace("\n", "  ")[:120], "...")

    model = est.fit(X, y)
    print(f"fit over {args.workers} workers; "
          f"val_loss {est.history_[-1]['val_loss']:.4f}; "
          f"w = {np.round(np.asarray(model.params['w']), 3)}")

    with tempfile.TemporaryDirectory() as d:
        est.save(os.path.join(d, "estimator"))
        model.write().save(os.path.join(d, "model"))
        est2 = JaxEstimator.load(os.path.join(d, "estimator"))
        model2 = load_ml(os.path.join(d, "model"))
        assert isinstance(model2, JaxModel)
        assert est2.getEpochs() == args.epochs
        err = float(np.abs(model2.predict(X) - model.predict(X)).max())
        print(f"persistence round-trip OK (pred delta {err:.2e})")

    err = float(np.abs(model.predict(X) - y).max())
    print(f"max |pred - y| = {err:.3f}")
    assert err < 0.2, "did not converge"
    print("estimator_pipeline example OK")


if __name__ == "__main__":
    main()
