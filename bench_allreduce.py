"""Allreduce bus-bandwidth microbenchmark — BASELINE.md's primary metric.

The reference's headline numbers are allreduce scaling efficiency measured
with dedicated benchmark harnesses (ref: docs/benchmarks.rst:8-43; the
synthetic harnesses :64-80).  This sweeps message sizes through the
data-plane allreduce on the dp mesh and reports, per size:

* ``algbw`` — algorithm bandwidth: message bytes / op time;
* ``busbw`` — bus bandwidth: ``algbw * 2(n-1)/n``, the ring-allreduce
  wire-traffic accounting, comparable across device counts (the
  convention the reference's NCCL-based numbers use).

Paths measured:

* ``jit`` (default) — the XLA device collective (``psum`` over the dp
  mesh axis), i.e. what ``DistributedOptimizer``'s fused gradient
  allreduce lowers to.  On multi-chip TPU this rides ICI.
* ``eager`` (``--eager``) — the negotiated eager path
  (``hvd.allreduce``), measuring the full controller+data-plane
  round trip per op (the reference's per-op latency analog).

``--wire {f32,bf16,fp16,int8}`` selects the wire format of the jit leg:
dtype casts around the psum for bf16/fp16 (``Compression.bf16/.fp16``),
or the block-scaled quantized two-stage collective for int8
(``Compression.int8`` — horovod_tpu/quant).  Non-f32 wires also time
the f32 leg and report ``speedup_vs_f32``; ``--json-out FILE`` writes
the sweep (bytes_on_wire, GB/s, speedup) as a JSON result file for the
BENCH trajectory, like bench.py does.

Runs anywhere: 8-device CPU sim for correctness/CI, a TPU slice for real
numbers.  Prints one human line per size and a final JSON summary line.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024:
            return f"{n:.0f}{unit}"
        n /= 1024
    return f"{n:.0f}TiB"


def _shard_map():
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn
    from jax.experimental.shard_map import shard_map  # older jax

    return shard_map


def wire_payload_bytes(count: int, dtype, wire: str) -> int:
    """Bytes one allreduce message occupies in the selected wire format
    (the compression accounting the JSON result file carries)."""
    import jax.numpy as jnp

    if wire in ("bf16", "fp16"):
        return count * 2
    if wire == "int8":
        from horovod_tpu.quant import wire_bytes

        return wire_bytes(count)
    return count * jnp.dtype(dtype).itemsize


def bench_jit(mesh, nbytes: int, dtype, inner: int, iters: int,
              warmup: int, wire: str = "f32"):
    """Per-op seconds for a chained allreduce of ``nbytes`` over the
    selected wire format."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.devices.size
    count = max(1, nbytes // jnp.dtype(dtype).itemsize)
    x = jax.device_put(
        jnp.ones((n, count), dtype),
        NamedSharding(mesh, P("dp")))
    cast_to = {"bf16": jnp.bfloat16, "fp16": jnp.float16}.get(wire)
    pcast = getattr(lax, "pcast", None)

    def body(xl):
        # inner chained allreduces per call amortize dispatch overhead;
        # the 1/n rescale keeps values bounded AND makes each iteration
        # depend on the last (no overlap/elision).
        def one(_, acc):
            if wire == "int8":
                from horovod_tpu.common.types import ReduceOp
                from horovod_tpu.quant import quantized_allreduce_flat

                red = quantized_allreduce_flat(
                    acc.reshape(-1), "dp",
                    op=ReduceOp.AVERAGE).reshape(acc.shape)
            else:
                w = acc.astype(cast_to) if cast_to is not None else acc
                red = (lax.psum(w, "dp") * (1.0 / n)).astype(acc.dtype)
            # psum output is replicated; pcast back to varying so the
            # fori_loop carry type is stable (no-op pre-vma-tracking
            # JAX builds, which have no pcast).
            return (pcast(red, ("dp",), to="varying")
                    if pcast is not None else red)
        return lax.fori_loop(0, inner, one, xl)

    f = jax.jit(_shard_map()(body, mesh=mesh, in_specs=P("dp"),
                             out_specs=P("dp")))

    def run_and_wait():
        # Force completion with a host fetch of a scalar that data-depends
        # on the result; block_until_ready can be a no-op on tunnelled
        # PJRT backends and would report fantasy bandwidth.
        float(jnp.sum(f(x)[..., :1].astype(jnp.float32)))

    for _ in range(warmup):
        run_and_wait()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        run_and_wait()
        times.append((time.perf_counter() - t0) / inner)
    return min(times)


def bench_eager(hvd, nbytes: int, dtype, iters: int, warmup: int):
    """Per-op seconds for the negotiated eager allreduce path."""
    import numpy as np

    count = max(1, nbytes // np.dtype(dtype).itemsize)
    x = np.ones((count,), dtype)
    for i in range(warmup):
        hvd.allreduce(x, name=f"bw_warm_{nbytes}_{i}")
    times = []
    for i in range(iters):
        t0 = time.perf_counter()
        np.asarray(hvd.allreduce(x, name=f"bw_{nbytes}_{i}"))
        times.append(time.perf_counter() - t0)
    return min(times)


def _eager_worker(sizes, dtype, iters):
    """Per-rank body for --np multi-process eager measurement: measures
    the full negotiate+host-collective round trip across real processes
    (the reference's per-op latency regime)."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd

    hvd.init()
    rows = []
    for nbytes in sizes:
        t = bench_eager(hvd, nbytes, dtype, iters, 2)
        rows.append({"bytes": nbytes, "eager_us": t * 1e6,
                     "eager_algbw_gbps": nbytes / t / 1e9})
    return {"rank": hvd.rank(), "size": hvd.size(), "rows": rows}


def _run_eager_multiproc(args) -> None:
    """--np N: spawn N real worker processes via the programmatic runner
    and report the negotiated eager path's latency/bandwidth sweep."""
    import functools

    from horovod_tpu import runner

    sizes = []
    s = args.min_bytes
    while s <= args.max_bytes:
        sizes.append(s)
        s *= 4
    results = runner.run(
        functools.partial(_eager_worker, sizes, args.dtype, args.iters),
        np=args.np)
    rows = results[0]["rows"]
    for row in rows:
        print(f"{_fmt_bytes(row['bytes']):>8}  eager {row['eager_us']:>10.1f}us "
              f"algbw {row['eager_algbw_gbps']:>8.3f} GB/s", file=sys.stderr)
    print(json.dumps({
        "metric": "eager_allreduce_sweep",
        "n_processes": args.np,
        "unit": "us",
        "rows": rows,
    }))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--min-bytes", type=int, default=1 << 12)
    ap.add_argument("--max-bytes", type=int, default=1 << 26)
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--inner", type=int, default=10,
                    help="chained allreduces per timed call (jit path)")
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--eager", action="store_true",
                    help="also measure the negotiated eager path")
    ap.add_argument("--wire", choices=("f32", "bf16", "fp16", "int8"),
                    default="f32",
                    help="wire format for the jit leg (int8 = the "
                         "block-scaled quantized collective, "
                         "horovod_tpu/quant; non-f32 also times the "
                         "f32 leg for speedup_vs_f32)")
    ap.add_argument("--json-out", default="",
                    help="also write the sweep JSON to this file "
                         "(bytes_on_wire / GB/s / speedup_vs_f32 rows)")
    ap.add_argument("--np", type=int, default=0,
                    help="measure the eager path across N real worker "
                         "processes (launched via the programmatic runner)")
    args = ap.parse_args()

    if args.np > 1:
        _run_eager_multiproc(args)
        return

    import jax
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    mesh = hvd.mesh()
    n = mesh.devices.size
    dev = jax.devices()[0]
    print(f"# allreduce sweep on {n}x {dev.platform}:{dev.device_kind} "
          f"(busbw = algbw * 2(n-1)/n)", file=sys.stderr)

    rows = []
    size = args.min_bytes
    factor = 2.0 * (n - 1) / n if n > 1 else 1.0
    while size <= args.max_bytes:
        t_jit = bench_jit(mesh, size, args.dtype, args.inner, args.iters,
                          args.warmup, wire=args.wire)
        count = max(1, size // np.dtype(args.dtype).itemsize)
        on_wire = wire_payload_bytes(count, args.dtype, args.wire)
        row = {"bytes": size, "jit_algbw_gbps": size / t_jit / 1e9,
               "jit_busbw_gbps": size / t_jit * factor / 1e9,
               "jit_us": t_jit * 1e6,
               "wire": args.wire, "bytes_on_wire": on_wire,
               "wire_gbps": on_wire / t_jit / 1e9}
        if args.wire != "f32":
            t_f32 = bench_jit(mesh, size, args.dtype, args.inner,
                              args.iters, args.warmup, wire="f32")
            row["f32_us"] = t_f32 * 1e6
            row["speedup_vs_f32"] = t_f32 / t_jit
        if args.eager:
            t_e = bench_eager(hvd, size, args.dtype,
                              max(3, args.iters // 2), 1)
            row["eager_algbw_gbps"] = size / t_e / 1e9
            row["eager_us"] = t_e * 1e6
        rows.append(row)
        msg = (f"{_fmt_bytes(size):>8}  jit {row['jit_us']:>10.1f}us "
               f"algbw {row['jit_algbw_gbps']:>8.2f} GB/s "
               f"busbw {row['jit_busbw_gbps']:>8.2f} GB/s")
        if args.wire != "f32":
            msg += (f"   wire={args.wire} {_fmt_bytes(on_wire):>8} "
                    f"speedup {row['speedup_vs_f32']:>5.2f}x")
        if args.eager:
            msg += (f"   eager {row['eager_us']:>10.1f}us "
                    f"algbw {row['eager_algbw_gbps']:>8.2f} GB/s")
        print(msg, file=sys.stderr)
        size *= 4

    peak = max(rows, key=lambda r: r["jit_busbw_gbps"])
    summary = {
        "metric": "allreduce_peak_busbw_gbps",
        "value": round(peak["jit_busbw_gbps"], 3),
        "unit": "GB/s",
        "n_devices": n,
        "platform": dev.platform,
        "at_bytes": peak["bytes"],
        "wire": args.wire,
        "rows": rows,
    }
    if args.wire != "f32":
        summary["speedup_vs_f32_at_peak"] = round(
            peak["speedup_vs_f32"], 3)
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(summary, f, indent=1)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
