"""Allreduce bus-bandwidth microbenchmark — BASELINE.md's primary metric.

The reference's headline numbers are allreduce scaling efficiency measured
with dedicated benchmark harnesses (ref: docs/benchmarks.rst:8-43; the
synthetic harnesses :64-80).  This sweeps message sizes through the
data-plane allreduce on the dp mesh and reports, per size:

* ``algbw`` — algorithm bandwidth: message bytes / op time;
* ``busbw`` — bus bandwidth: ``algbw * 2(n-1)/n``, the ring-allreduce
  wire-traffic accounting, comparable across device counts (the
  convention the reference's NCCL-based numbers use).

Paths measured:

* ``jit`` (default) — the XLA device collective (``psum`` over the dp
  mesh axis), i.e. what ``DistributedOptimizer``'s fused gradient
  allreduce lowers to.  On multi-chip TPU this rides ICI.
* ``eager`` (``--eager``) — the negotiated eager path
  (``hvd.allreduce``), measuring the full controller+data-plane
  round trip per op (the reference's per-op latency analog).

Runs anywhere: 8-device CPU sim for correctness/CI, a TPU slice for real
numbers.  Prints one human line per size and a final JSON summary line.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024:
            return f"{n:.0f}{unit}"
        n /= 1024
    return f"{n:.0f}TiB"


def bench_jit(mesh, nbytes: int, dtype, inner: int, iters: int,
              warmup: int):
    """Per-op seconds for a chained psum allreduce of ``nbytes``."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.devices.size
    count = max(1, nbytes // jnp.dtype(dtype).itemsize)
    x = jax.device_put(
        jnp.ones((n, count), dtype),
        NamedSharding(mesh, P("dp")))

    def body(xl):
        # inner chained allreduces per call amortize dispatch overhead;
        # the 1/n rescale keeps values bounded AND makes each iteration
        # depend on the last (no overlap/elision).
        def one(_, acc):
            red = lax.psum(acc, "dp") * (1.0 / n)
            # psum output is replicated; pcast back to varying so the
            # fori_loop carry type is stable.
            return lax.pcast(red, ("dp",), to="varying")
        return lax.fori_loop(0, inner, one, xl)

    f = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("dp"),
                              out_specs=P("dp")))

    def run_and_wait():
        # Force completion with a host fetch of a scalar that data-depends
        # on the result; block_until_ready can be a no-op on tunnelled
        # PJRT backends and would report fantasy bandwidth.
        float(jnp.sum(f(x)[..., :1].astype(jnp.float32)))

    for _ in range(warmup):
        run_and_wait()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        run_and_wait()
        times.append((time.perf_counter() - t0) / inner)
    return min(times)


def bench_eager(hvd, nbytes: int, dtype, iters: int, warmup: int):
    """Per-op seconds for the negotiated eager allreduce path."""
    import numpy as np

    count = max(1, nbytes // np.dtype(dtype).itemsize)
    x = np.ones((count,), dtype)
    for i in range(warmup):
        hvd.allreduce(x, name=f"bw_warm_{nbytes}_{i}")
    times = []
    for i in range(iters):
        t0 = time.perf_counter()
        np.asarray(hvd.allreduce(x, name=f"bw_{nbytes}_{i}"))
        times.append(time.perf_counter() - t0)
    return min(times)


def _eager_worker(sizes, dtype, iters):
    """Per-rank body for --np multi-process eager measurement: measures
    the full negotiate+host-collective round trip across real processes
    (the reference's per-op latency regime)."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd

    hvd.init()
    rows = []
    for nbytes in sizes:
        t = bench_eager(hvd, nbytes, dtype, iters, 2)
        rows.append({"bytes": nbytes, "eager_us": t * 1e6,
                     "eager_algbw_gbps": nbytes / t / 1e9})
    return {"rank": hvd.rank(), "size": hvd.size(), "rows": rows}


def _run_eager_multiproc(args) -> None:
    """--np N: spawn N real worker processes via the programmatic runner
    and report the negotiated eager path's latency/bandwidth sweep."""
    import functools

    from horovod_tpu import runner

    sizes = []
    s = args.min_bytes
    while s <= args.max_bytes:
        sizes.append(s)
        s *= 4
    results = runner.run(
        functools.partial(_eager_worker, sizes, args.dtype, args.iters),
        np=args.np)
    rows = results[0]["rows"]
    for row in rows:
        print(f"{_fmt_bytes(row['bytes']):>8}  eager {row['eager_us']:>10.1f}us "
              f"algbw {row['eager_algbw_gbps']:>8.3f} GB/s", file=sys.stderr)
    print(json.dumps({
        "metric": "eager_allreduce_sweep",
        "n_processes": args.np,
        "unit": "us",
        "rows": rows,
    }))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--min-bytes", type=int, default=1 << 12)
    ap.add_argument("--max-bytes", type=int, default=1 << 26)
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--inner", type=int, default=10,
                    help="chained allreduces per timed call (jit path)")
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--eager", action="store_true",
                    help="also measure the negotiated eager path")
    ap.add_argument("--np", type=int, default=0,
                    help="measure the eager path across N real worker "
                         "processes (launched via the programmatic runner)")
    args = ap.parse_args()

    if args.np > 1:
        _run_eager_multiproc(args)
        return

    import jax
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    mesh = hvd.mesh()
    n = mesh.devices.size
    dev = jax.devices()[0]
    print(f"# allreduce sweep on {n}x {dev.platform}:{dev.device_kind} "
          f"(busbw = algbw * 2(n-1)/n)", file=sys.stderr)

    rows = []
    size = args.min_bytes
    factor = 2.0 * (n - 1) / n if n > 1 else 1.0
    while size <= args.max_bytes:
        t_jit = bench_jit(mesh, size, args.dtype, args.inner, args.iters,
                          args.warmup)
        row = {"bytes": size, "jit_algbw_gbps": size / t_jit / 1e9,
               "jit_busbw_gbps": size / t_jit * factor / 1e9,
               "jit_us": t_jit * 1e6}
        if args.eager:
            t_e = bench_eager(hvd, size, args.dtype,
                              max(3, args.iters // 2), 1)
            row["eager_algbw_gbps"] = size / t_e / 1e9
            row["eager_us"] = t_e * 1e6
        rows.append(row)
        msg = (f"{_fmt_bytes(size):>8}  jit {row['jit_us']:>10.1f}us "
               f"algbw {row['jit_algbw_gbps']:>8.2f} GB/s "
               f"busbw {row['jit_busbw_gbps']:>8.2f} GB/s")
        if args.eager:
            msg += (f"   eager {row['eager_us']:>10.1f}us "
                    f"algbw {row['eager_algbw_gbps']:>8.2f} GB/s")
        print(msg, file=sys.stderr)
        size *= 4

    peak = max(rows, key=lambda r: r["jit_busbw_gbps"])
    print(json.dumps({
        "metric": "allreduce_peak_busbw_gbps",
        "value": round(peak["jit_busbw_gbps"], 3),
        "unit": "GB/s",
        "n_devices": n,
        "platform": dev.platform,
        "at_bytes": peak["bytes"],
        "rows": rows,
    }))


if __name__ == "__main__":
    main()
