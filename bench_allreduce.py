"""Allreduce bus-bandwidth microbenchmark — BASELINE.md's primary metric.

The reference's headline numbers are allreduce scaling efficiency measured
with dedicated benchmark harnesses (ref: docs/benchmarks.rst:8-43; the
synthetic harnesses :64-80).  This sweeps message sizes through the
data-plane allreduce on the dp mesh and reports, per size:

* ``algbw`` — algorithm bandwidth: message bytes / op time;
* ``busbw`` — bus bandwidth: ``algbw * 2(n-1)/n``, the ring-allreduce
  wire-traffic accounting, comparable across device counts (the
  convention the reference's NCCL-based numbers use).

Paths measured:

* ``jit`` (default) — the XLA device collective (``psum`` over the dp
  mesh axis), i.e. what ``DistributedOptimizer``'s fused gradient
  allreduce lowers to.  On multi-chip TPU this rides ICI.
* ``eager`` (``--eager``) — the negotiated eager path
  (``hvd.allreduce``), measuring the full controller+data-plane
  round trip per op (the reference's per-op latency analog).

``--wire {f32,bf16,fp16,int8,int4}`` selects the wire format of the jit leg:
dtype casts around the psum for bf16/fp16 (``Compression.bf16/.fp16``),
or the block-scaled quantized two-stage collective for int8/int4
(``Compression.int8`` / ``.int4`` — horovod_tpu/quant; int4 packs two
4-bit lanes per byte on the wire).  Non-f32 wires also time
the f32 leg and report ``speedup_vs_f32``; ``--json-out FILE`` writes
the sweep (bytes_on_wire, GB/s, speedup) as a JSON result file for the
BENCH trajectory, like bench.py does.

``--hierarchical`` measures the transport-policy data plane
(horovod_tpu/transport) on a two-level (outer × inner) mesh: per size
it times the flat psum over both axes, the hierarchical allreduce under
``--transport`` (default ``auto``), and each tier in isolation —
emitting one row per (axis, algorithm, wire, size) plus a measured
``hierarchical_speedup_vs_flat`` column.  The summary's
``hierarchical_speedup_vs_flat_at_peak`` is what
``HVDT_AUTOTUNE_TRANSPORT_SEED`` reads to seed the autotuner's
transport dimension — policies are measured, not guessed.

Runs anywhere: 8-device CPU sim for correctness/CI, a TPU slice for real
numbers.  Prints one human line per size and a final JSON summary line.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

# Normalized row schema (the horovod_tpu.analysis.costmodel fitter's
# input contract): every sweep row carries `axis`, `algorithm`, `wire`,
# `size_bytes`, `seconds`, `axis_size` next to its legacy columns, and
# every summary carries `schema_version`.  tools/fit_costmodel.py
# regenerates the checked-in calibration from any set of these files.
SCHEMA_VERSION = 1


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024:
            return f"{n:.0f}{unit}"
        n /= 1024
    return f"{n:.0f}TiB"


def _shard_map():
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn
    from jax.experimental.shard_map import shard_map  # older jax

    return shard_map


def wire_payload_bytes(count: int, dtype, wire: str) -> int:
    """Bytes one allreduce message occupies in the selected wire format
    (the compression accounting the JSON result file carries)."""
    import jax.numpy as jnp

    if wire in ("bf16", "fp16"):
        return count * 2
    if wire == "int8":
        from horovod_tpu.quant import wire_bytes

        return wire_bytes(count)
    if wire == "int4":
        from horovod_tpu.quant import wire_bytes_int4

        return wire_bytes_int4(count)
    return count * jnp.dtype(dtype).itemsize


def bench_jit(mesh, nbytes: int, dtype, inner: int, iters: int,
              warmup: int, wire: str = "f32"):
    """Per-op seconds for a chained allreduce of ``nbytes`` over the
    selected wire format."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.devices.size
    count = max(1, nbytes // jnp.dtype(dtype).itemsize)
    x = jax.device_put(
        jnp.ones((n, count), dtype),
        NamedSharding(mesh, P("dp")))
    cast_to = {"bf16": jnp.bfloat16, "fp16": jnp.float16}.get(wire)
    pcast = getattr(lax, "pcast", None)

    def body(xl):
        # inner chained allreduces per call amortize dispatch overhead;
        # the 1/n rescale keeps values bounded AND makes each iteration
        # depend on the last (no overlap/elision).
        def one(_, acc):
            if wire in ("int8", "int4"):
                from horovod_tpu.common.types import ReduceOp
                from horovod_tpu.quant import quantized_allreduce_flat

                red = quantized_allreduce_flat(
                    acc.reshape(-1), "dp",
                    op=ReduceOp.AVERAGE, wire=wire).reshape(acc.shape)
            else:
                w = acc.astype(cast_to) if cast_to is not None else acc
                red = (lax.psum(w, "dp") * (1.0 / n)).astype(acc.dtype)
            # psum output is replicated; pcast back to varying so the
            # fori_loop carry type is stable (no-op pre-vma-tracking
            # JAX builds, which have no pcast).
            return (pcast(red, ("dp",), to="varying")
                    if pcast is not None else red)
        return lax.fori_loop(0, inner, one, xl)

    f = jax.jit(_shard_map()(body, mesh=mesh, in_specs=P("dp"),
                             out_specs=P("dp")))

    def run_and_wait():
        # Force completion with a host fetch of a scalar that data-depends
        # on the result; block_until_ready can be a no-op on tunnelled
        # PJRT backends and would report fantasy bandwidth.
        float(jnp.sum(f(x)[..., :1].astype(jnp.float32)))

    for _ in range(warmup):
        run_and_wait()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        run_and_wait()
        times.append((time.perf_counter() - t0) / inner)
    return min(times)


def _build_mesh2d(outer: int):
    """(outer × inner) mesh with ('dcn', 'ici') axes — the two-level
    topology the hierarchical sweep measures (outer = the slow tier)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devs = jax.devices()
    n = len(devs)
    if outer < 2 or n % outer:
        outer = 2 if (n >= 4 and n % 2 == 0) else 0
    if not outer:
        raise SystemExit(
            f"--hierarchical needs an even device count >= 4 to split "
            f"into (outer, inner); have {n}")
    return Mesh(np.asarray(devs, dtype=object).reshape(outer, n // outer),
                ("dcn", "ici"))


def bench_hier_jit(mesh, nbytes: int, dtype, inner: int, iters: int,
                   warmup: int, leg: str):
    """Per-op seconds for one leg of the hierarchical sweep on the
    ('dcn', 'ici') mesh: ``flat`` = psum over both axes, ``hier`` = the
    transport-policy hierarchical allreduce, ``ici``/``dcn`` = one tier
    in isolation (fast reduce-scatter+allgather / slow shard
    exchange)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.common.types import ReduceOp
    from horovod_tpu.ops import device as hdev

    n_dcn, n_ici = (mesh.devices.shape[0], mesh.devices.shape[1])
    n = n_dcn * n_ici
    count = max(n_ici, nbytes // jnp.dtype(dtype).itemsize)
    count -= count % n_ici      # shard evenly over the fast tier
    if leg == "dcn":
        count //= n_ici         # the slow tier moves the 1/n_ici shard
    x = jax.device_put(jnp.ones((n, count), dtype),
                       NamedSharding(mesh, P(("dcn", "ici"))))
    pcast = getattr(lax, "pcast", None)

    def body(xl):
        def one(_, acc):
            if leg == "flat":
                red = lax.psum(acc, ("dcn", "ici")) * (1.0 / n)
            elif leg == "hier":
                # fused_allreduce resolves the HVDT_TRANSPORT policy at
                # trace time and routes hierarchically.
                red = hdev.fused_allreduce(
                    [acc.reshape(-1)], ("dcn", "ici"),
                    ReduceOp.AVERAGE)[0].reshape(acc.shape)
            elif leg == "ici":
                shard = lax.psum_scatter(acc.reshape(-1), "ici",
                                         tiled=True)
                red = hdev.invariant_allgather_shards(
                    shard, "ici").reshape(acc.shape) * (1.0 / n_ici)
            else:   # dcn: the slow shard exchange in isolation
                red = lax.psum(acc, "dcn") * (1.0 / n_dcn)
            return (pcast(red, ("dcn", "ici"), to="varying")
                    if pcast is not None else red)

        return lax.fori_loop(0, inner, one, xl)

    f = jax.jit(_shard_map()(body, mesh=mesh,
                             in_specs=P(("dcn", "ici")),
                             out_specs=P(("dcn", "ici"))))

    def run_and_wait():
        float(jnp.sum(f(x)[..., :1].astype(jnp.float32)))

    for _ in range(warmup):
        run_and_wait()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        run_and_wait()
        times.append((time.perf_counter() - t0) / inner)
    return min(times)


def bench_rs_jit(mesh, nbytes: int, dtype, inner: int, iters: int,
                 warmup: int, leg: str):
    """Per-op seconds for one leg of the reduce-scatter sweep on the dp
    mesh: ``allreduce`` = the flat psum, ``rs_ag`` = the explicit
    reduce-scatter + invariant-allgather split (the HVDT_ZERO=grads
    wire), ``rs`` = the reduce-scatter hop alone (what the deeper ZeRO
    stages pay per step when the allgather is deferred into the
    parameter-delta path)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.ops import device as hdev

    n = mesh.devices.size
    count = max(n, nbytes // jnp.dtype(dtype).itemsize)
    count -= count % n
    x = jax.device_put(jnp.ones((n, count), dtype),
                       NamedSharding(mesh, P("dp")))
    pcast = getattr(lax, "pcast", None)

    def body(xl):
        def one(_, acc):
            flat = acc.reshape(-1)
            if leg == "allreduce":
                red = lax.psum(flat, "dp") * (1.0 / n)
            elif leg == "rs_ag":
                shard = hdev.reduce_scatter_flat(flat, "dp")
                red = hdev.allgather_flat_shards(shard, "dp") * (1.0 / n)
            else:   # rs: the wire hop alone; tile back so the carry
                    # chains (labelled approximate — the tile is local)
                shard = hdev.reduce_scatter_flat(flat, "dp")
                red = jnp.tile(shard, n) * (1.0 / n)
            red = red.reshape(acc.shape)
            return (pcast(red, ("dp",), to="varying")
                    if pcast is not None else red)

        return lax.fori_loop(0, inner, one, xl)

    f = jax.jit(_shard_map()(body, mesh=mesh, in_specs=P("dp"),
                             out_specs=P("dp")))

    def run_and_wait():
        float(jnp.sum(f(x)[..., :1].astype(jnp.float32)))

    for _ in range(warmup):
        run_and_wait()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        run_and_wait()
        times.append((time.perf_counter() - t0) / inner)
    return min(times)


def _run_reduce_scatter(args) -> None:
    """--reduce-scatter: measure the ZeRO wire split against the flat
    allreduce per message size and emit ``rs_ag_speedup_vs_allreduce``
    rows — the measured seed ``HVDT_AUTOTUNE_ZERO_SEED`` reads (the
    autotuner's replicated-vs-sharded starting leg comes from this
    file, not a guess — mirrors HVDT_AUTOTUNE_TRANSPORT_SEED)."""
    import jax

    import horovod_tpu as hvd

    hvd.init()
    mesh = hvd.mesh()
    n = mesh.devices.size
    dev0 = jax.devices()[0]
    print(f"# reduce-scatter sweep on {n}x "
          f"{dev0.platform}:{dev0.device_kind} "
          f"(rs_ag = explicit RS+AG split, the HVDT_ZERO wire)",
          file=sys.stderr)

    rows = []
    size = args.min_bytes
    while size <= args.max_bytes:
        t = {leg: bench_rs_jit(mesh, size, args.dtype, args.inner,
                               args.iters, args.warmup, leg)
             for leg in ("allreduce", "rs_ag", "rs")}
        speedup = (t["allreduce"] / t["rs_ag"]
                   if t["rs_ag"] > 0 else None)
        rows.append({
            "bytes": size, "size_bytes": size,
            "axis": "dp", "axis_size": int(n),
            "algorithm": "rs_ag", "wire": "f32",
            "seconds": t["rs_ag"],
            "allreduce_us": t["allreduce"] * 1e6,
            "rs_ag_us": t["rs_ag"] * 1e6,
            "rs_us": t["rs"] * 1e6,
            "rs_ag_algbw_gbps": size / t["rs_ag"] / 1e9,
            "rs_ag_speedup_vs_allreduce": speedup,
            "deferred_ag_fraction": (1.0 - t["rs"] / t["rs_ag"]
                                     if t["rs_ag"] > 0 else None),
        })
        print(f"{_fmt_bytes(size):>8}  allreduce "
              f"{t['allreduce']*1e6:>9.1f}us  rs+ag "
              f"{t['rs_ag']*1e6:>9.1f}us  rs {t['rs']*1e6:>9.1f}us  "
              f"speedup {speedup:>5.2f}x", file=sys.stderr)
        size *= 4

    peak = max(rows, key=lambda r: r["rs_ag_algbw_gbps"])
    summary = {
        "metric": "reduce_scatter_sweep",
        "schema_version": SCHEMA_VERSION,
        "value": round(peak["rs_ag_speedup_vs_allreduce"], 3),
        "unit": "speedup_vs_allreduce",
        "n_devices": int(n),
        "platform": dev0.platform,
        "at_bytes": peak["bytes"],
        "rs_ag_speedup_vs_allreduce_at_peak": round(
            peak["rs_ag_speedup_vs_allreduce"], 3),
        "rows": rows,
    }
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(summary, f, indent=1)
    print(json.dumps(summary))


def bench_a2a_jit(mesh, nbytes: int, dtype, inner: int, iters: int,
                  warmup: int, wire: str = "f32"):
    """Per-op seconds for a chained ``all_to_all`` of ``nbytes`` on the
    dp mesh — the MoE expert-dispatch exchange
    (``parallel/moe._a2a_transport``), measured through the production
    transport path so an int8 leg times exactly what an
    ``HVDT_TRANSPORT=ep:ring:int8:...`` policy line buys: block-scaled
    int8 payload + f32 scale alltoalls with quantize/dequantize on
    either side (the gamma term), not a bare int8 exchange."""
    import os

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.parallel.moe import _a2a_transport
    from horovod_tpu.transport import policy as tpolicy

    n = mesh.devices.size
    count = max(n, nbytes // jnp.dtype(dtype).itemsize)
    count -= count % n
    c = count // n
    # Global [n, n, c] sharded on dim 0: each rank holds one [n, c]
    # dispatch block whose slice i is bound for rank i — the MoE
    # dispatch layout.
    x = jax.device_put(jnp.ones((n, n, c), dtype),
                       NamedSharding(mesh, P("dp")))
    pcast = getattr(lax, "pcast", None)

    prev = os.environ.get("HVDT_TRANSPORT")
    if wire == "f32":
        os.environ.pop("HVDT_TRANSPORT", None)
    else:
        os.environ["HVDT_TRANSPORT"] = f"dp:ring:{wire}:64M"
    tpolicy.reset()
    try:
        def body(xl):
            def one(_, acc):
                # a2a permutes blocks across ranks, so chaining the
                # output back as the next input keeps values bounded
                # while forcing each iteration to wait for the last.
                out = _a2a_transport(acc[0], "dp", "bench")[None]
                return (pcast(out, ("dp",), to="varying")
                        if pcast is not None else out)

            return lax.fori_loop(0, inner, one, xl)

        f = jax.jit(_shard_map()(body, mesh=mesh, in_specs=P("dp"),
                                 out_specs=P("dp")))

        def run_and_wait():
            float(jnp.sum(f(x)[..., :1].astype(jnp.float32)))

        for _ in range(warmup):
            run_and_wait()
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            run_and_wait()
            times.append((time.perf_counter() - t0) / inner)
        return min(times)
    finally:
        if prev is None:
            os.environ.pop("HVDT_TRANSPORT", None)
        else:
            os.environ["HVDT_TRANSPORT"] = prev
        tpolicy.reset()


def _run_a2a(args) -> None:
    """--a2a: sweep the expert-dispatch ``all_to_all`` per message size,
    f32 against the block-scaled int8 MoE wire, and emit
    ``op="all_to_all"`` rows.

    The rows feed ``analysis.costmodel.fit_from_bench`` (via
    tools/fit_costmodel.py) alongside the allreduce sweeps: (alpha,
    beta) are LINK constants with per-op geometry factored out row by
    row, so a2a rows sharpen the same fit that prices
    ``CostModel.alltoall_seconds`` — which is what the autotuner's
    MoE capacity-factor dimension's model seed
    (``predict_leg_order(...)["moe"]``) consults.  Rows deliberately
    omit ``bytes_on_wire`` so the fitter applies a2a geometry
    (``(n-1)/n``) itself."""
    import jax

    import horovod_tpu as hvd

    hvd.init()
    mesh = hvd.mesh()
    n = mesh.devices.size
    dev0 = jax.devices()[0]
    print(f"# all_to_all sweep on {n}x "
          f"{dev0.platform}:{dev0.device_kind} "
          f"(the MoE expert-dispatch wire; int8 = block-scaled "
          f"payload + f32 scales)", file=sys.stderr)

    import numpy as np

    rows = []
    size = args.min_bytes
    while size <= args.max_bytes:
        t_f32 = bench_a2a_jit(mesh, size, args.dtype, args.inner,
                              args.iters, args.warmup, wire="f32")
        t_int8 = bench_a2a_jit(mesh, size, args.dtype, args.inner,
                               args.iters, args.warmup, wire="int8")
        count = max(1, size // np.dtype(args.dtype).itemsize)
        speedup = t_f32 / t_int8 if t_int8 > 0 else None
        for wire, secs in (("f32", t_f32), ("int8", t_int8)):
            rows.append({
                "bytes": size, "size_bytes": size,
                "axis": "dp", "axis_size": int(n),
                "algorithm": "ring", "wire": wire,
                "op": "all_to_all",
                "seconds": secs,
                "a2a_us": secs * 1e6,
                "a2a_algbw_gbps": size / secs / 1e9,
                "a2a_wire_bytes": wire_payload_bytes(
                    count, args.dtype, wire),
                "int8_speedup_vs_f32": speedup,
            })
        print(f"{_fmt_bytes(size):>8}  f32 {t_f32*1e6:>9.1f}us  "
              f"int8 {t_int8*1e6:>9.1f}us  "
              f"speedup {speedup:>5.2f}x", file=sys.stderr)
        size *= 4

    peak = max((r for r in rows if r["wire"] == "f32"),
               key=lambda r: r["a2a_algbw_gbps"])
    summary = {
        "metric": "a2a_sweep",
        "schema_version": SCHEMA_VERSION,
        "value": round(peak["int8_speedup_vs_f32"], 3),
        "unit": "int8_speedup_vs_f32",
        "n_devices": int(n),
        "platform": dev0.platform,
        "at_bytes": peak["bytes"],
        "int8_a2a_speedup_vs_f32_at_peak": round(
            peak["int8_speedup_vs_f32"], 3),
        "rows": rows,
    }
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(summary, f, indent=1)
    print(json.dumps(summary))


def _run_hierarchical(args) -> None:
    """--hierarchical: the per-(axis, algorithm, wire, size) sweep of
    the transport-policy data plane, with the measured
    hierarchical-vs-flat verdict the autotune transport dimension
    seeds from."""
    import os

    os.environ.setdefault("HVDT_TRANSPORT", args.transport or "auto")

    import jax

    import horovod_tpu as hvd
    from horovod_tpu.quant import wire_bytes as q_wire_bytes
    from horovod_tpu.transport import get_policy

    hvd.init()
    mesh = _build_mesh2d(args.outer)
    n_dcn, n_ici = mesh.devices.shape
    pol = get_policy()
    res = pol.resolve(("dcn", "ici"))
    dev0 = jax.devices()[0]
    item = 4 if args.dtype == "float32" else 2
    print(f"# hierarchical allreduce sweep on {n_dcn}x{n_ici} "
          f"{dev0.platform}:{dev0.device_kind} policy={pol.describe()}",
          file=sys.stderr)

    def _wire_item(wire):
        return {"bf16": 2, "fp16": 2}.get(wire, item)

    rows = []
    size = args.min_bytes
    while size <= args.max_bytes:
        count = max(n_ici, size // item)
        count -= count % n_ici
        shard = count // n_ici
        t = {leg: bench_hier_jit(mesh, size, args.dtype, args.inner,
                                 args.iters, args.warmup, leg)
             for leg in ("flat", "hier", "ici", "dcn")}
        # Per-tier ring wire accounting: RS+AG over ici moves
        # 2(k-1)/k of the payload; the slow tier exchanges the 1/k
        # shard (int8: payload + block scales via quant.wire_bytes).
        ici_wire = 2 * count * _wire_item(res.fast.wire) \
            * (n_ici - 1) // n_ici
        if res.slow.wire == "int8":
            dcn_wire = int(q_wire_bytes(shard))
        elif res.slow.wire == "int4":
            from horovod_tpu.quant import wire_bytes_int4 as q_wire4

            dcn_wire = int(q_wire4(shard))
        else:
            dcn_wire = 2 * shard * _wire_item(res.slow.wire) \
                * (n_dcn - 1) // max(1, n_dcn)
        speedup = t["flat"] / t["hier"] if t["hier"] > 0 else None
        n = n_dcn * n_ici
        flat_wire = 2 * count * item * (n - 1) // n
        rows.extend([
            {"bytes": size, "size_bytes": size, "axis": "ici",
             "axis_size": int(n_ici),
             "algorithm": res.fast.algorithm, "wire": res.fast.wire,
             "us": t["ici"] * 1e6, "seconds": t["ici"],
             "bytes_on_wire": ici_wire,
             "wire_gbps": ici_wire / t["ici"] / 1e9},
            {"bytes": size, "size_bytes": size, "axis": "dcn",
             "axis_size": int(n_dcn),
             "algorithm": res.slow.algorithm, "wire": res.slow.wire,
             "us": t["dcn"] * 1e6, "seconds": t["dcn"],
             "bytes_on_wire": dcn_wire,
             "wire_gbps": dcn_wire / t["dcn"] / 1e9},
            {"bytes": size, "size_bytes": size, "axis": "ici+dcn",
             "axis_size": int(n), "algorithm": "flat",
             "wire": args.dtype if args.dtype != "float32" else "f32",
             "us": t["flat"] * 1e6, "seconds": t["flat"],
             "bytes_on_wire": flat_wire,
             "wire_gbps": flat_wire / t["flat"] / 1e9},
            {"bytes": size, "size_bytes": size, "axis": "ici+dcn",
             "axis_size": int(n), "algorithm": "hierarchical",
             "wire": f"{res.fast.wire}/{res.slow.wire}",
             "us": t["hier"] * 1e6, "seconds": t["hier"],
             "flat_us": t["flat"] * 1e6,
             "bytes_on_wire": ici_wire + dcn_wire,
             "jit_algbw_gbps": size / t["hier"] / 1e9,
             "hierarchical_speedup_vs_flat": speedup},
        ])
        print(f"{_fmt_bytes(size):>8}  flat {t['flat']*1e6:>9.1f}us  "
              f"hier {t['hier']*1e6:>9.1f}us  speedup {speedup:>5.2f}x  "
              f"(ici {t['ici']*1e6:.1f}us dcn {t['dcn']*1e6:.1f}us)",
              file=sys.stderr)
        size *= 4

    hier_rows = [r for r in rows if r["algorithm"] == "hierarchical"]
    peak = max(hier_rows, key=lambda r: r["jit_algbw_gbps"])
    summary = {
        "metric": "allreduce_hierarchical_sweep",
        "schema_version": SCHEMA_VERSION,
        "value": round(peak["hierarchical_speedup_vs_flat"], 3),
        "unit": "speedup_vs_flat",
        "n_devices": int(n_dcn * n_ici),
        "mesh": {"dcn": int(n_dcn), "ici": int(n_ici)},
        "platform": dev0.platform,
        "transport": os.environ.get("HVDT_TRANSPORT", ""),
        "at_bytes": peak["bytes"],
        "hierarchical_speedup_vs_flat_at_peak": round(
            peak["hierarchical_speedup_vs_flat"], 3),
        "rows": rows,
    }
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(summary, f, indent=1)
    print(json.dumps(summary))


def bench_eager(hvd, nbytes: int, dtype, iters: int, warmup: int):
    """Per-op seconds for the negotiated eager allreduce path."""
    import numpy as np

    count = max(1, nbytes // np.dtype(dtype).itemsize)
    x = np.ones((count,), dtype)
    for i in range(warmup):
        hvd.allreduce(x, name=f"bw_warm_{nbytes}_{i}")
    times = []
    for i in range(iters):
        t0 = time.perf_counter()
        np.asarray(hvd.allreduce(x, name=f"bw_{nbytes}_{i}"))
        times.append(time.perf_counter() - t0)
    return min(times)


def _eager_worker(sizes, dtype, iters):
    """Per-rank body for --np multi-process eager measurement: measures
    the full negotiate+host-collective round trip across real processes
    (the reference's per-op latency regime)."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd

    hvd.init()
    rows = []
    for nbytes in sizes:
        t = bench_eager(hvd, nbytes, dtype, iters, 2)
        rows.append({"bytes": nbytes, "eager_us": t * 1e6,
                     "eager_algbw_gbps": nbytes / t / 1e9})
    return {"rank": hvd.rank(), "size": hvd.size(), "rows": rows}


def _run_eager_multiproc(args) -> None:
    """--np N: spawn N real worker processes via the programmatic runner
    and report the negotiated eager path's latency/bandwidth sweep."""
    import functools

    from horovod_tpu import runner

    sizes = []
    s = args.min_bytes
    while s <= args.max_bytes:
        sizes.append(s)
        s *= 4
    results = runner.run(
        functools.partial(_eager_worker, sizes, args.dtype, args.iters),
        np=args.np)
    rows = results[0]["rows"]
    for row in rows:
        print(f"{_fmt_bytes(row['bytes']):>8}  eager {row['eager_us']:>10.1f}us "
              f"algbw {row['eager_algbw_gbps']:>8.3f} GB/s", file=sys.stderr)
    print(json.dumps({
        "metric": "eager_allreduce_sweep",
        "n_processes": args.np,
        "unit": "us",
        "rows": rows,
    }))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--min-bytes", type=int, default=1 << 12)
    ap.add_argument("--max-bytes", type=int, default=1 << 26)
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--inner", type=int, default=10,
                    help="chained allreduces per timed call (jit path)")
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--eager", action="store_true",
                    help="also measure the negotiated eager path")
    ap.add_argument("--wire",
                    choices=("f32", "bf16", "fp16", "int8", "int4"),
                    default="f32",
                    help="wire format for the jit leg (int8/int4 = the "
                         "block-scaled quantized collective, "
                         "horovod_tpu/quant; non-f32 also times the "
                         "f32 leg for speedup_vs_f32)")
    ap.add_argument("--json-out", default="",
                    help="also write the sweep JSON to this file "
                         "(axis / algorithm / bytes_on_wire / GB/s / "
                         "speedup rows)")
    ap.add_argument("--reduce-scatter", action="store_true",
                    help="measure the explicit reduce-scatter + "
                         "allgather split (the HVDT_ZERO wire) against "
                         "the flat allreduce; emits "
                         "rs_ag_speedup_vs_allreduce rows (the "
                         "HVDT_AUTOTUNE_ZERO_SEED input)")
    ap.add_argument("--a2a", action="store_true",
                    help="measure the MoE expert-dispatch all_to_all "
                         "(f32 vs the block-scaled int8 transport "
                         "wire); emits op=all_to_all rows for the "
                         "cost-model fitter and "
                         "int8_a2a_speedup_vs_f32_at_peak")
    ap.add_argument("--hierarchical", action="store_true",
                    help="two-level transport-policy sweep on an "
                         "(outer x inner) mesh: per-(axis, algorithm, "
                         "wire, size) rows + measured "
                         "hierarchical_speedup_vs_flat (the "
                         "HVDT_AUTOTUNE_TRANSPORT_SEED input)")
    ap.add_argument("--transport", default="",
                    help="HVDT_TRANSPORT policy spec for the "
                         "hierarchical sweep (e.g. 'ici:ring:f32:64M,"
                         "dcn:tree:int8:8M'; default 'auto')")
    ap.add_argument("--outer", type=int, default=2,
                    help="slow-axis (dcn) size for --hierarchical; "
                         "must divide the device count")
    ap.add_argument("--np", type=int, default=0,
                    help="measure the eager path across N real worker "
                         "processes (launched via the programmatic runner)")
    args = ap.parse_args()

    if args.np > 1:
        _run_eager_multiproc(args)
        return
    if args.reduce_scatter:
        _run_reduce_scatter(args)
        return
    if args.a2a:
        _run_a2a(args)
        return
    if args.hierarchical or args.transport:
        _run_hierarchical(args)
        return

    import jax
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    mesh = hvd.mesh()
    n = mesh.devices.size
    dev = jax.devices()[0]
    print(f"# allreduce sweep on {n}x {dev.platform}:{dev.device_kind} "
          f"(busbw = algbw * 2(n-1)/n)", file=sys.stderr)

    rows = []
    size = args.min_bytes
    factor = 2.0 * (n - 1) / n if n > 1 else 1.0
    while size <= args.max_bytes:
        t_jit = bench_jit(mesh, size, args.dtype, args.inner, args.iters,
                          args.warmup, wire=args.wire)
        count = max(1, size // np.dtype(args.dtype).itemsize)
        on_wire = wire_payload_bytes(count, args.dtype, args.wire)
        row = {"bytes": size, "size_bytes": size,
               "jit_algbw_gbps": size / t_jit / 1e9,
               "jit_busbw_gbps": size / t_jit * factor / 1e9,
               "jit_us": t_jit * 1e6, "seconds": t_jit,
               "axis": "dp", "axis_size": int(n), "algorithm": "flat",
               "wire": args.wire, "bytes_on_wire": on_wire,
               "wire_gbps": on_wire / t_jit / 1e9}
        if args.wire != "f32":
            t_f32 = bench_jit(mesh, size, args.dtype, args.inner,
                              args.iters, args.warmup, wire="f32")
            row["f32_us"] = t_f32 * 1e6
            row["speedup_vs_f32"] = t_f32 / t_jit
        if args.eager:
            t_e = bench_eager(hvd, size, args.dtype,
                              max(3, args.iters // 2), 1)
            row["eager_algbw_gbps"] = size / t_e / 1e9
            row["eager_us"] = t_e * 1e6
        rows.append(row)
        msg = (f"{_fmt_bytes(size):>8}  jit {row['jit_us']:>10.1f}us "
               f"algbw {row['jit_algbw_gbps']:>8.2f} GB/s "
               f"busbw {row['jit_busbw_gbps']:>8.2f} GB/s")
        if args.wire != "f32":
            msg += (f"   wire={args.wire} {_fmt_bytes(on_wire):>8} "
                    f"speedup {row['speedup_vs_f32']:>5.2f}x")
        if args.eager:
            msg += (f"   eager {row['eager_us']:>10.1f}us "
                    f"algbw {row['eager_algbw_gbps']:>8.2f} GB/s")
        print(msg, file=sys.stderr)
        size *= 4

    peak = max(rows, key=lambda r: r["jit_busbw_gbps"])
    summary = {
        "metric": "allreduce_peak_busbw_gbps",
        "schema_version": SCHEMA_VERSION,
        "value": round(peak["jit_busbw_gbps"], 3),
        "unit": "GB/s",
        "n_devices": n,
        "platform": dev.platform,
        "at_bytes": peak["bytes"],
        "wire": args.wire,
        "rows": rows,
    }
    if args.wire != "f32":
        summary["speedup_vs_f32_at_peak"] = round(
            peak["speedup_vs_f32"], 3)
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(summary, f, indent=1)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
