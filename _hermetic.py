"""Hermetic CPU-only child environments for driver entry points.

Single source of truth for the accelerator env scrub used by
``bench.py`` and ``__graft_entry__.py`` (both live at the repo root and
must not import the framework — their parent processes stay JAX-free).

A CPU child must drop every var that selects a JAX platform OR that
makes an accelerator site-hook (tunnelled-TPU PJRT plugin registration
at interpreter startup) do remote work: if the tunnel/relay is
unhealthy, a child that keeps those vars hangs before executing a
single line of our code.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

PLATFORM_VARS = ("JAX_PLATFORMS", "JAX_PLATFORM_NAME", "XLA_FLAGS")
ACCEL_PREFIXES = ("PALLAS_AXON", "AXON_", "TPU_", "LIBTPU", "PJRT_")


def scrubbed_cpu_env(host_device_count: Optional[int] = None,
                     base: Optional[Dict[str, str]] = None
                     ) -> Dict[str, str]:
    """Copy of ``base`` (default os.environ) pinned to the CPU platform
    with every accelerator-steering var removed; optionally forces
    ``host_device_count`` virtual CPU devices."""
    src = os.environ if base is None else base
    env = {k: v for k, v in src.items()
           if k not in PLATFORM_VARS and not k.startswith(ACCEL_PREFIXES)}
    env["JAX_PLATFORMS"] = "cpu"
    if host_device_count is not None:
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={host_device_count}")
    return env
